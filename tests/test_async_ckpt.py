"""Async checkpointing tests (reference analog: tests/checkpointing/unit/test_async_save.py
and test_async_writer.py) — real spawn workers, sharded arrays on the 8-device
CPU mesh, failure injection."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_resiliency.checkpointing import AsyncCheckpointer, load_checkpoint
from tpu_resiliency.checkpointing.async_ckpt.core import (
    AsyncCallsQueue,
    AsyncRequest,
    CheckpointSaveError,
    store_sync_fn,
)
from tpu_resiliency.checkpointing.async_ckpt.writer import is_committed, read_metadata


def make_tree(seed=0):
    k = jax.random.PRNGKey(seed)
    return {
        "params": {
            "w": jax.random.normal(k, (16, 32), dtype=jnp.float32),
            "b": jnp.zeros((32,), dtype=jnp.float32),
        },
        "step": jnp.int32(7),
        "plain_numpy": np.arange(5, dtype=np.int64),
    }


def assert_trees_equal(a, b):
    la, _ = jax.tree_util.tree_flatten(a)
    lb, _ = jax.tree_util.tree_flatten(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_sync_save_load_roundtrip(tmp_path):
    ckpt = AsyncCheckpointer(persistent_worker=True)
    tree = make_tree()
    d = str(tmp_path / "ck1")
    ckpt.save(tree, d)
    assert is_committed(d)
    restored = load_checkpoint(d, jax.tree_util.tree_map(np.zeros_like, tree))
    assert_trees_equal(tree, restored)
    ckpt.close()


def test_async_save_overlaps_and_finalizes(tmp_path):
    ckpt = AsyncCheckpointer()
    tree = make_tree()
    d = str(tmp_path / "ck2")
    idx = ckpt.async_save(tree, d)
    assert idx == 1
    # not necessarily committed yet; finalize loop commits it
    deadline = time.monotonic() + 30
    while not is_committed(d):
        ckpt.maybe_finalize(blocking=False)
        assert time.monotonic() < deadline
        time.sleep(0.02)
    restored = load_checkpoint(d, tree)
    assert_trees_equal(tree, restored)
    ckpt.close()


def test_sharded_tree_roundtrip(tmp_path):
    devs = jax.devices()
    assert len(devs) == 8, "conftest must provide 8 virtual devices"
    mesh = Mesh(np.array(devs).reshape(4, 2), ("data", "model"))
    sh = NamedSharding(mesh, P("data", "model"))
    repl = NamedSharding(mesh, P())
    x = jax.device_put(jnp.arange(64.0).reshape(8, 8), sh)
    y = jax.device_put(jnp.ones((4, 4)), repl)
    tree = {"x": x, "y": y}

    ckpt = AsyncCheckpointer()
    d = str(tmp_path / "ck3")
    ckpt.save(tree, d)
    meta = read_metadata(d)
    # sharded leaf wrote one shard per device, replicated leaf exactly one
    x_leaf = meta["leaf_paths"].index("['x']")
    y_leaf = meta["leaf_paths"].index("['y']")
    assert sum(1 for s in meta["shards"] if s["leaf_idx"] == x_leaf) == 8
    assert sum(1 for s in meta["shards"] if s["leaf_idx"] == y_leaf) == 1
    restored = load_checkpoint(d, tree)
    assert_trees_equal(tree, restored)
    assert restored["x"].sharding.is_equivalent_to(sh, 2)
    ckpt.close()


def test_restore_into_different_mesh_and_sharding(tmp_path):
    """The elastic-restart case: the mesh that loads a checkpoint is NOT
    the mesh that saved it (world shrank/grew, axes re-shaped).  Values
    must survive exactly and land in the TEMPLATE's sharding — restore is
    template-driven, not save-layout-driven."""
    devs = jax.devices()
    save_mesh = Mesh(np.array(devs).reshape(4, 2), ("data", "model"))
    x = jax.device_put(
        jnp.arange(64.0).reshape(8, 8),
        NamedSharding(save_mesh, P("data", "model")),
    )
    y = jax.device_put(
        jnp.arange(16.0).reshape(4, 4), NamedSharding(save_mesh, P())
    )
    tree = {"x": x, "y": y}
    ckpt = AsyncCheckpointer()
    d = str(tmp_path / "ck-elastic")
    ckpt.save(tree, d)
    ckpt.close()

    # a "restarted job": transposed axes AND a different factorization
    load_mesh = Mesh(np.array(devs).reshape(2, 4), ("model", "data"))
    new_sh = NamedSharding(load_mesh, P("data", "model"))
    template = {
        "x": jax.device_put(jnp.zeros((8, 8)), new_sh),
        "y": jax.device_put(jnp.zeros((4, 4)),
                            NamedSharding(load_mesh, P("model"))),
    }
    restored = load_checkpoint(d, template)
    assert np.array_equal(np.asarray(restored["x"]), np.asarray(x))
    assert np.array_equal(np.asarray(restored["y"]), np.asarray(y))
    assert restored["x"].sharding.is_equivalent_to(new_sh, 2)
    # pure-dp single-axis mesh too (the common shrink-to-survivors shape)
    dp_mesh = Mesh(np.array(devs), ("dp",))
    dp_sh = NamedSharding(dp_mesh, P("dp"))
    template2 = {
        "x": jax.device_put(jnp.zeros((8, 8)), dp_sh),
        "y": jax.device_put(jnp.zeros((4, 4)), NamedSharding(dp_mesh, P())),
    }
    restored2 = load_checkpoint(d, template2)
    assert np.array_equal(np.asarray(restored2["x"]), np.asarray(x))
    assert restored2["x"].sharding.is_equivalent_to(dp_sh, 2)


def test_multiple_pending_saves_finalize_in_order(tmp_path):
    ckpt = AsyncCheckpointer()
    dirs = [str(tmp_path / f"it{i}") for i in range(3)]
    for i, d in enumerate(dirs):
        ckpt.async_save(make_tree(seed=i), d)
    ckpt.finalize_all()
    for i, d in enumerate(dirs):
        assert is_committed(d)
        restored = load_checkpoint(d, make_tree(seed=i))
        assert_trees_equal(make_tree(seed=i), restored)
    ckpt.close()


def _failing_write(*args):
    raise RuntimeError("disk on fire")


def test_failed_async_write_surfaces_error():
    q = AsyncCallsQueue()
    q.schedule_async_request(AsyncRequest(async_fn=_failing_write))
    with pytest.raises(CheckpointSaveError, match="disk on fire"):
        q.maybe_finalize_async_calls(blocking=True, timeout=30)
    q.caller.close()


def test_store_sync_fn_consensus(store):
    # rank 0 done, rank 1 not -> not globally done; both done -> done
    sync0 = store_sync_fn(store, rank=0, world_size=2, namespace="t1")
    sync1 = store_sync_fn(store, rank=1, world_size=2, namespace="t1")
    assert sync0(1, True) is False      # rank1 hasn't reported
    assert sync1(1, False) is False
    assert sync1(1, True) is True
    assert sync0(1, True) is True


class _ApplyThenRaiseAdd:
    """Store proxy: ADD applies server-side, then the client sees a failure —
    the ambiguous window the client never retries (bytes left, op non-idempotent)."""

    def __init__(self, store, fail_times: int):
        self._s = store
        self.fail_times = fail_times
        self.add_calls = 0

    def add(self, key, amount: int = 1) -> int:
        from tpu_resiliency.store.client import StoreError

        self.add_calls += 1
        out = self._s.add(key, amount)
        if self.fail_times > 0:
            self.fail_times -= 1
            raise StoreError("connection lost after send")
        return out

    def __getattr__(self, name):
        return getattr(self._s, name)


def test_store_sync_fn_ambiguous_add_never_overcounts(store):
    """An ADD that applied but raised client-side must not be re-applied by a
    later sync() call: the counter must never exceed the true vouch count, or
    finalize would commit a torn checkpoint."""
    flaky = _ApplyThenRaiseAdd(store, fail_times=1)
    sync0 = store_sync_fn(flaky, rank=0, world_size=2, namespace="amb")
    # rank 0's ADD applies but raises; swallowed, marker remains the truth
    assert sync0(0, True) is False
    # repeated polls must not bump the counter again
    assert sync0(0, True) is False
    assert sync0(0, True) is False
    assert int(store.try_get("amb/done_count/0")) == 1
    sync1 = store_sync_fn(store, rank=1, world_size=2, namespace="amb")
    assert sync1(0, True) is True
    assert sync0(0, True) is True


def test_store_sync_fn_recreated_closure_is_idempotent(store):
    """Recreating the sync closure mid-cycle (last_published resets) must not
    double-vouch: world_size must never be reached while a rank is unfinished."""
    sync0a = store_sync_fn(store, rank=0, world_size=2, namespace="rec")
    assert sync0a(2, True) is False  # vouches calls 0..2
    # closure recreated (e.g. checkpointer rebuilt mid-cycle)
    sync0b = store_sync_fn(store, rank=0, world_size=2, namespace="rec")
    assert sync0b(2, True) is False  # must NOT re-bump counters 0..2
    for idx in range(3):
        assert int(store.try_get(f"rec/done_count/{idx}")) == 1
    sync1 = store_sync_fn(store, rank=1, world_size=2, namespace="rec")
    assert sync1(2, True) is True


def test_store_sync_fn_heals_lost_add(store):
    """If an ADD is lost entirely (marker set, counter short), the marker
    recount must still reach consensus and repair the counter write-through."""
    # simulate rank 0's lost ADD: marker present, counter never bumped
    store.set("heal/vouch/0/r0", b"1")
    sync1 = store_sync_fn(store, rank=1, world_size=2, namespace="heal")
    # the recount path is throttled; within ~1s of polls it must heal
    healed = any(sync1(0, True) for _ in range(25))
    assert healed  # marker recount: 2 markers >= world
    # write-through repair for other pollers' fast path
    assert int(store.try_get("heal/done_count/0")) >= 2


def test_uncommitted_checkpoint_rejected(tmp_path):
    d = tmp_path / "partial"
    d.mkdir()
    (d / "process_0.json").write_text("{}")
    with pytest.raises(FileNotFoundError):
        load_checkpoint(str(d), {"a": np.zeros(1)})


# -- snapshot staging + plan/shm reuse (round 2) ------------------------------


def test_snapshot_mode_donation_safe(tmp_path):
    """The save must capture the state AT save time even when the very next
    dispatch donates and overwrites the saved buffers."""
    mesh = Mesh(np.array(jax.devices()), ("all",))
    sh = NamedSharding(mesh, P("all"))
    x = jax.device_put(jnp.arange(64, dtype=jnp.float32), sh)
    bump = jax.jit(lambda v: v + 1.0, donate_argnums=(0,))
    ckpt = AsyncCheckpointer(stage_mode="snapshot")
    d = str(tmp_path / "snap")
    ckpt.async_save({"x": x}, d)
    expected = np.asarray(jnp.arange(64, dtype=jnp.float32))
    x = bump(x)  # donates + overwrites the buffer the save references
    x = bump(x)
    ckpt.finalize_all()
    restored = load_checkpoint(d, {"x": np.zeros(64, dtype=np.float32)})
    np.testing.assert_array_equal(restored["x"], expected)
    ckpt.close()


def test_steady_state_save_reuses_shm(tmp_path):
    """Second save of an unchanged layout must allocate zero new shm bytes
    (plan + segment reuse; reference worker cache core.py:434-438)."""
    ckpt = AsyncCheckpointer(stage_mode="snapshot")
    tree = make_tree()
    d1, d2, d3 = (str(tmp_path / f"ck{i}") for i in range(3))
    ckpt.async_save(tree, d1)
    ckpt.finalize_all()
    assert ckpt.last_stage_stats["bytes_allocated"] > 0
    ckpt.async_save(make_tree(seed=1), d2)
    ckpt.finalize_all()
    assert ckpt.last_stage_stats["bytes_allocated"] == 0
    assert ckpt.last_stage_stats["bytes_reused"] > 0
    # values are the NEW tree's, not the pooled buffers' old contents
    restored = load_checkpoint(d2, jax.tree_util.tree_map(np.zeros_like, tree))
    assert_trees_equal(make_tree(seed=1), restored)
    # layout change invalidates reuse and still saves correctly
    other = {"y": np.arange(17, dtype=np.float32)}
    ckpt.async_save(other, d3)
    ckpt.finalize_all()
    assert ckpt.last_stage_stats["bytes_allocated"] > 0
    restored = load_checkpoint(d3, {"y": np.zeros(17, dtype=np.float32)})
    np.testing.assert_array_equal(restored["y"], other["y"])
    ckpt.close()


def test_metadata_merge_cache_verified(tmp_path):
    """Rank-0 merge cache is reused only when every process index reports the
    same plan signature (verify_global_md_reuse analog)."""
    ckpt = AsyncCheckpointer(stage_mode="sync")
    tree = make_tree()
    d1, d2 = str(tmp_path / "m1"), str(tmp_path / "m2")
    ckpt.async_save(tree, d1)
    ckpt.finalize_all()
    assert ckpt._merger.reuse_hits == 0
    ckpt.async_save(make_tree(seed=2), d2)
    ckpt.finalize_all()
    assert ckpt._merger.reuse_hits == 1
    meta = read_metadata(d2)
    assert meta["plan_sig"]
    restored = load_checkpoint(d2, jax.tree_util.tree_map(np.zeros_like, tree))
    assert_trees_equal(make_tree(seed=2), restored)
    ckpt.close()


# -- pipelined chunked drain (this PR) ----------------------------------------


def test_chunked_bfloat16_roundtrip_bit_exact(tmp_path, monkeypatch):
    """Shards split into many chunks (unaligned bfloat16 tail included) must
    round-trip bit-exact through the multi-writer drain."""
    monkeypatch.setenv("TPURX_CKPT_CHUNK_BYTES", "8192")  # force real chunking
    tree = {
        # 3 aligned chunks + unaligned 2050-byte tail, odd shape
        "w": jax.random.normal(jax.random.PRNGKey(3), (13301,)).astype(jnp.bfloat16),
        "b": jnp.arange(7, dtype=jnp.bfloat16),   # sub-chunk, unaligned
        "empty": jnp.zeros((0,), dtype=jnp.bfloat16),
        "f32": jnp.arange(4096.0),                # exactly chunk-aligned
    }
    ckpt = AsyncCheckpointer()
    d = str(tmp_path / "bf16")
    ckpt.save(tree, d)
    # on-disk shard files carry the raw little-endian bytes (layout is
    # chunk-invariant: same bytes whether written in 1 write or N pwrites)
    meta = read_metadata(d)
    w_leaf = meta["leaf_paths"].index("['w']")
    fn = tmp_path / "bf16" / "process_0" / f"shard_{w_leaf}_0.bin"
    assert fn.read_bytes() == np.asarray(tree["w"]).tobytes()
    assert not fn.parent.joinpath(fn.name + ".tmp").exists()
    restored = load_checkpoint(d, jax.tree_util.tree_map(np.zeros_like, tree))
    for k in tree:
        got, want = np.asarray(restored[k]), np.asarray(tree[k])
        assert got.dtype == want.dtype
        np.testing.assert_array_equal(got.view(np.uint16) if k != "f32" else got,
                                      want.view(np.uint16) if k != "f32" else want)
    ckpt.close()


def test_interrupted_drain_commits_nothing(tmp_path):
    """Atomic commit: a worker killed mid-drain must leave NO metadata.json
    (readers fall back to the last committed checkpoint) and the failure
    must surface as CheckpointSaveError."""
    ckpt = AsyncCheckpointer()
    prev = str(tmp_path / "good")
    tree = make_tree()
    ckpt.save(tree, prev)
    assert is_committed(prev)

    # a save big enough that the drain is still in flight when we kill
    big = {"x": jnp.ones((4 << 20,), dtype=jnp.float32)}  # 16 MiB
    d2 = str(tmp_path / "doomed")
    ckpt.async_save(big, d2)
    ckpt.queue.caller._ensure_worker().kill()  # the interruption
    with pytest.raises(CheckpointSaveError):
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            ckpt.maybe_finalize(blocking=False)
            time.sleep(0.02)
    assert not is_committed(d2)  # no metadata.json ⇒ never half-committed
    with pytest.raises(FileNotFoundError):
        load_checkpoint(d2, big)
    # the previous checkpoint is untouched and still loads
    restored = load_checkpoint(prev, jax.tree_util.tree_map(np.zeros_like, tree))
    assert_trees_equal(tree, restored)
    ckpt.close()


def test_second_save_reuses_shm_segments(tmp_path):
    """The staging pool must hand the SAME shm segments (by name) to the
    second save of an identically-shaped tree — reuse, not re-create."""
    ckpt = AsyncCheckpointer()
    d1, d2 = str(tmp_path / "r1"), str(tmp_path / "r2")
    ckpt.save(make_tree(seed=0), d1)
    first = {s.shm_name for s in ckpt._pool[0].shards if s.replica_owner}
    assert first
    ckpt.save(make_tree(seed=1), d2)
    second = {s.shm_name for s in ckpt._pool[0].shards if s.replica_owner}
    assert second == first  # identical segments, rewritten in place
    assert ckpt.last_stage_stats["bytes_allocated"] == 0
    assert ckpt.last_stage_stats["bytes_reused"] > 0
    restored = load_checkpoint(d2, jax.tree_util.tree_map(np.zeros_like, make_tree()))
    assert_trees_equal(make_tree(seed=1), restored)
    ckpt.close()


def test_snapshot_staging_error_surfaces(tmp_path):
    """A staging failure in the background thread must raise from
    maybe_finalize/finalize_all, not vanish."""
    ckpt = AsyncCheckpointer(stage_mode="snapshot")

    class Boom:
        shape = ()
        dtype = np.float32

        def __array__(self, dtype=None, copy=None):
            raise RuntimeError("boom")

    ckpt.async_save({"bad": Boom()}, str(tmp_path / "er"))
    with pytest.raises(CheckpointSaveError, match="staging failed"):
        ckpt.finalize_all()
    ckpt.close()


def test_drain_progress_monotonic_and_terminal(tmp_path, monkeypatch):
    """PR 1's drain_progress(): (written, total) is monotonic 0→total while
    the save is in flight (reaching written == total once the worker's final
    progress frame lands) and terminal (0, 0) after finalize empties the
    in-flight set."""
    monkeypatch.setenv("TPURX_CKPT_CHUNK_BYTES", str(1 << 20))  # many frames
    ckpt = AsyncCheckpointer()
    tree = {"big": np.ones((8 << 20,), np.float32)}  # 32 MiB, 32 chunks
    d = str(tmp_path / "prog")
    ckpt.async_save(tree, d, save_id="p")
    samples = []
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        samples.append(ckpt.drain_progress())  # call stays pending: no
        w, t = samples[-1]                     # maybe_finalize in this loop
        if t and w == t:
            break
        time.sleep(0.005)
    ckpt.finalize_all()
    terminal = ckpt.drain_progress()
    ckpt.close()
    assert is_committed(d)
    written_seq = [w for w, _t in samples]
    assert written_seq == sorted(written_seq), "drain progress went backwards"
    totals = {t for _w, t in samples if t}
    assert totals == {tree["big"].nbytes}, f"unexpected totals {totals}"
    assert samples[-1] == (tree["big"].nbytes, tree["big"].nbytes)  # reached 1.0
    assert terminal == (0, 0)  # nothing in flight after finalize
