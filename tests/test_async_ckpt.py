"""Async checkpointing tests (reference analog: tests/checkpointing/unit/test_async_save.py
and test_async_writer.py) — real spawn workers, sharded arrays on the 8-device
CPU mesh, failure injection."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_resiliency.checkpointing import AsyncCheckpointer, load_checkpoint
from tpu_resiliency.checkpointing.async_ckpt.core import (
    AsyncCallsQueue,
    AsyncRequest,
    CheckpointSaveError,
    store_sync_fn,
)
from tpu_resiliency.checkpointing.async_ckpt.writer import is_committed, read_metadata


def make_tree(seed=0):
    k = jax.random.PRNGKey(seed)
    return {
        "params": {
            "w": jax.random.normal(k, (16, 32), dtype=jnp.float32),
            "b": jnp.zeros((32,), dtype=jnp.float32),
        },
        "step": jnp.int32(7),
        "plain_numpy": np.arange(5, dtype=np.int64),
    }


def assert_trees_equal(a, b):
    la, _ = jax.tree_util.tree_flatten(a)
    lb, _ = jax.tree_util.tree_flatten(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_sync_save_load_roundtrip(tmp_path):
    ckpt = AsyncCheckpointer(persistent_worker=True)
    tree = make_tree()
    d = str(tmp_path / "ck1")
    ckpt.save(tree, d)
    assert is_committed(d)
    restored = load_checkpoint(d, jax.tree_util.tree_map(np.zeros_like, tree))
    assert_trees_equal(tree, restored)
    ckpt.close()


def test_async_save_overlaps_and_finalizes(tmp_path):
    ckpt = AsyncCheckpointer()
    tree = make_tree()
    d = str(tmp_path / "ck2")
    idx = ckpt.async_save(tree, d)
    assert idx == 1
    # not necessarily committed yet; finalize loop commits it
    deadline = time.monotonic() + 30
    while not is_committed(d):
        ckpt.maybe_finalize(blocking=False)
        assert time.monotonic() < deadline
        time.sleep(0.02)
    restored = load_checkpoint(d, tree)
    assert_trees_equal(tree, restored)
    ckpt.close()


def test_sharded_tree_roundtrip(tmp_path):
    devs = jax.devices()
    assert len(devs) == 8, "conftest must provide 8 virtual devices"
    mesh = Mesh(np.array(devs).reshape(4, 2), ("data", "model"))
    sh = NamedSharding(mesh, P("data", "model"))
    repl = NamedSharding(mesh, P())
    x = jax.device_put(jnp.arange(64.0).reshape(8, 8), sh)
    y = jax.device_put(jnp.ones((4, 4)), repl)
    tree = {"x": x, "y": y}

    ckpt = AsyncCheckpointer()
    d = str(tmp_path / "ck3")
    ckpt.save(tree, d)
    meta = read_metadata(d)
    # sharded leaf wrote one shard per device, replicated leaf exactly one
    x_leaf = meta["leaf_paths"].index("['x']")
    y_leaf = meta["leaf_paths"].index("['y']")
    assert sum(1 for s in meta["shards"] if s["leaf_idx"] == x_leaf) == 8
    assert sum(1 for s in meta["shards"] if s["leaf_idx"] == y_leaf) == 1
    restored = load_checkpoint(d, tree)
    assert_trees_equal(tree, restored)
    assert restored["x"].sharding.is_equivalent_to(sh, 2)
    ckpt.close()


def test_multiple_pending_saves_finalize_in_order(tmp_path):
    ckpt = AsyncCheckpointer()
    dirs = [str(tmp_path / f"it{i}") for i in range(3)]
    for i, d in enumerate(dirs):
        ckpt.async_save(make_tree(seed=i), d)
    ckpt.finalize_all()
    for i, d in enumerate(dirs):
        assert is_committed(d)
        restored = load_checkpoint(d, make_tree(seed=i))
        assert_trees_equal(make_tree(seed=i), restored)
    ckpt.close()


def _failing_write(*args):
    raise RuntimeError("disk on fire")


def test_failed_async_write_surfaces_error():
    q = AsyncCallsQueue()
    q.schedule_async_request(AsyncRequest(async_fn=_failing_write))
    with pytest.raises(CheckpointSaveError, match="disk on fire"):
        q.maybe_finalize_async_calls(blocking=True, timeout=30)
    q.caller.close()


def test_store_sync_fn_consensus(store):
    # rank 0 done, rank 1 not -> not globally done; both done -> done
    sync0 = store_sync_fn(store, rank=0, world_size=2, namespace="t1")
    sync1 = store_sync_fn(store, rank=1, world_size=2, namespace="t1")
    assert sync0(1, True) is False      # rank1 hasn't reported
    assert sync1(1, False) is False
    assert sync1(1, True) is True
    assert sync0(1, True) is True


def test_uncommitted_checkpoint_rejected(tmp_path):
    d = tmp_path / "partial"
    d.mkdir()
    (d / "process_0.json").write_text("{}")
    with pytest.raises(FileNotFoundError):
        load_checkpoint(str(d), {"a": np.zeros(1)})
