"""Device-side digest path tests: on-device fingerprints, D2H-skipping
delta saves, the double-buffered snapshot ring, and sharding-derived save
planning.

Four properties anchor the zero-stall save path:

- the jitted fingerprint kernel and the numpy host oracle compute the SAME
  per-chunk (A, B) rows for every lane-bitcastable dtype — bfloat16
  included — so a device-vs-baseline match means what the drain thinks it
  means;
- a delta save under an active device digest skips the D2H entirely for
  unchanged shards, yet every restore rung (resident shm, peer exchange,
  cold disk) reproduces the bytes exactly, because the skip records
  base-generation provenance instead of bytes;
- device/host verdict disagreement on a transferred chunk is DETECTED
  corruption: the save fails closed, the partial output is quarantined as
  ``*.corrupt``, nothing commits;
- the owner map derived from ``NamedSharding`` assigns every global index
  box to exactly one device cluster-wide, and refuses shardings that
  over- or under-tile the global shape.
"""

import contextlib
import glob
import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_resiliency.checkpointing.async_ckpt import (
    checkpointer as ckpt_mod,
    device_digest as dd,
    resident as resident_mod,
    staging as staging_mod,
    writer as writer_mod,
)
from tpu_resiliency.checkpointing.async_ckpt.checkpointer import (
    AsyncCheckpointer,
    CheckpointSaveError,
    load_checkpoint,
)
from tpu_resiliency.checkpointing.async_ckpt.peer_source import (
    PeerRestoreSource,
)
from tpu_resiliency.checkpointing.local.replication import PeerExchange
from tpu_resiliency.store import StoreClient


@pytest.fixture(autouse=True)
def _fresh_registry():
    resident_mod.invalidate()
    yield
    resident_mod.invalidate()


def assert_trees_equal(a, b):
    la, _ = jax.tree_util.tree_flatten(a)
    lb, _ = jax.tree_util.tree_flatten(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        ax, ay = np.asarray(x), np.asarray(y)
        assert ax.dtype == ay.dtype
        assert ax.tobytes() == ay.tobytes()  # byte-identical, not just ==


# -- kernel vs host oracle ---------------------------------------------------


class TestFingerprintKernel:
    CHUNK = 1024  # force multi-chunk grids on small arrays

    @pytest.mark.parametrize(
        "dtype",
        ["float32", "bfloat16", "float16", "int32", "int8", "uint16", "bool"],
    )
    def test_device_matches_host_oracle(self, dtype):
        """The jitted kernel and the numpy oracle agree per chunk, per
        dtype — the exact agreement the drain's cross-check relies on."""
        rng = np.random.default_rng(7)
        host = rng.standard_normal(3001).astype(np.float32)
        x = jnp.asarray(host).astype(dtype)
        host_np = np.asarray(x)  # post-cast bytes (ml_dtypes for bfloat16)

        fp_dev = dd.shard_fingerprints(x, chunk_bytes=self.CHUNK,
                                       use_direct=False)
        assert fp_dev is not None
        (rows_dev,) = dd.read_fingerprints([fp_dev])
        rows_host = dd.host_fingerprints(
            host_np.tobytes(), host_np.dtype, chunk_bytes=self.CHUNK,
            use_direct=False,
        )
        grid = writer_mod.chunk_grid(host_np.nbytes, self.CHUNK, False)
        assert len(grid) > 1, "test must exercise a multi-chunk grid"
        assert rows_dev.shape == (len(grid), 2)
        np.testing.assert_array_equal(rows_dev, rows_host)

    def test_mutation_flips_only_its_chunk(self):
        x = jnp.arange(2048, dtype=jnp.float32)
        y = x.at[700].set(-1.0)  # byte offset 2800 -> second 1 KiB chunk
        (ra,) = dd.read_fingerprints(
            [dd.shard_fingerprints(x, chunk_bytes=self.CHUNK, use_direct=False)]
        )
        (rb,) = dd.read_fingerprints(
            [dd.shard_fingerprints(y, chunk_bytes=self.CHUNK, use_direct=False)]
        )
        changed = [i for i in range(ra.shape[0])
                   if not np.array_equal(ra[i], rb[i])]
        assert changed == [2]  # offset 2800 lands in chunk index 2

    def test_swapped_lanes_change_the_fingerprint(self):
        """The position-mixed lanes make reorderings visible — a plain
        multiset-preserving swap must not fingerprint equal."""
        x = jnp.asarray(np.array([1, 2, 3, 4], dtype=np.uint32))
        y = jnp.asarray(np.array([2, 1, 3, 4], dtype=np.uint32))
        (ra,) = dd.read_fingerprints([dd.shard_fingerprints(x)])
        (rb,) = dd.read_fingerprints([dd.shard_fingerprints(y)])
        assert not np.array_equal(ra, rb)

    def test_uniform_constant_bump_changes_fingerprint(self):
        """Regression: raw Fletcher sums telescope to ZERO on a uniform
        constant delta across a power-of-two-length chunk (`full(0.)` ->
        `full(1.)` fingerprinted equal, silently skipping a changed
        shard).  The avalanche mix must break the telescope."""
        n = 1 << 20
        x = jnp.full((n,), 0.0, jnp.float32)
        y = x + 1.0
        (ra,) = dd.read_fingerprints([dd.shard_fingerprints(x)])
        (rb,) = dd.read_fingerprints([dd.shard_fingerprints(y)])
        assert ra.shape == rb.shape
        for i in range(ra.shape[0]):
            assert not np.array_equal(ra[i], rb[i])

    def test_unsupported_dtype_stays_on_crc_path(self):
        assert dd.shard_fingerprints(jnp.ones(8, jnp.complex64)) is None
        assert dd.host_fingerprints(b"\x00" * 64, np.complex64) is None


# -- delta D2H-skip end to end ----------------------------------------------


def _big_tree(mutate=()):
    """~10 leaves; ``mutate`` names leaves whose values differ."""
    tree = {}
    for i in range(8):
        base = np.full(4096 + 128 * i, float(i + 1), dtype=np.float32)
        if f"f{i}" in mutate:
            base[17] = -99.0
        tree[f"f{i}"] = jnp.asarray(base)
    bf = np.arange(2048, dtype=np.float32) % 7.0
    if "bf" in mutate:
        bf[0] = 5.5
    tree["bf"] = jnp.asarray(bf).astype(jnp.bfloat16)
    tree["host"] = np.arange(33, dtype=np.int64)  # host leaf: never skips
    return tree


class TestDeltaD2HSkip:
    def test_unchanged_shards_skip_the_transfer(self, tmp_path):
        """Mutate ~10% of leaves; every unchanged device shard must skip
        D2H entirely, and all three generations restore byte-identically
        from disk (the sparse files resolve provenance) AND from the
        resident shm source."""
        d = str(tmp_path)
        ck = AsyncCheckpointer(delta=True, digest=True, device_digest=True)
        try:
            t1 = _big_tree()
            ck.save(t1, d + "/g1", {"iteration": 1})
            assert ck.last_stage_stats["d2h_skipped_bytes"] == 0  # no baseline

            t2 = _big_tree(mutate=("f3",))  # 1 of 10 leaves changes
            ck.save(t2, d + "/g2", {"iteration": 2})
            dev_total = sum(
                np.asarray(v).nbytes for k, v in t2.items() if k != "host"
            )
            changed = np.asarray(t2["f3"]).nbytes
            assert ck.last_stage_stats["d2h_skipped_bytes"] == dev_total - changed
            assert ck.last_drain_stats.get("d2h_skipped_bytes") == \
                dev_total - changed

            # provenance rows in the committed index point at g1's files
            idx = json.load(open(d + "/g2/process_0.json"))
            skip_shards = [s for s in idx["shards"] if s.get("bases")]
            assert skip_shards, "no provenance-only shards recorded"
            assert all("g1" in b for s in skip_shards for b in s["bases"])

            # warm (resident) restore of the delta generation
            warm = load_checkpoint(d + "/g2", t2, stats=(st := {}))
            assert_trees_equal(warm, t2)
            assert st.get("bytes_shm", 0) > 0
        finally:
            ck.close()
        # cold restores of every generation, resident source gone
        resident_mod.invalidate()
        for g, ref in (("g1", t1), ("g2", t2)):
            out = load_checkpoint(d + "/" + g, ref, resident=False)
            assert_trees_equal(out, ref)

    def test_fully_frozen_save_writes_nothing(self, tmp_path):
        d = str(tmp_path)
        ck = AsyncCheckpointer(delta=True, digest=True, device_digest=True)
        try:
            t = _big_tree()
            ck.save(t, d + "/g1", {"iteration": 1})
            ck.save(t, d + "/g2", {"iteration": 2})
            dev_total = sum(
                np.asarray(v).nbytes for k, v in t.items() if k != "host"
            )
            assert ck.last_stage_stats["d2h_skipped_bytes"] == dev_total
            assert ck.last_drain_stats.get("bytes_written", 0) == 0
        finally:
            ck.close()
        resident_mod.invalidate()
        assert_trees_equal(load_checkpoint(d + "/g2", t, resident=False), t)

    def test_peer_rung_restores_skipped_generation(self, tmp_path, store_server):
        """Satellite 1: with local files gone, ``load_checkpoint(peers=...)``
        pulls the shards from a peer's resident copy over the exchange —
        including a generation whose save skipped D2H."""
        c0 = StoreClient("127.0.0.1", store_server.port, timeout=10.0)
        c1 = StoreClient("127.0.0.1", store_server.port, timeout=10.0)
        ex0, ex1 = PeerExchange(c0, 0), PeerExchange(c1, 1)
        d = str(tmp_path)
        ck = AsyncCheckpointer(delta=True, digest=True, device_digest=True)
        src0 = src1 = None
        try:
            t1 = _big_tree()
            ck.save(t1, d + "/g1", {"iteration": 1})
            t2 = _big_tree(mutate=("f5",))
            ck.save(t2, d + "/g2", {"iteration": 2})
            assert ck.last_stage_stats["d2h_skipped_bytes"] > 0
            src0 = PeerRestoreSource(ex0, 0, [1]).install()  # serves resident
            src1 = PeerRestoreSource(ex1, 1, [0]).install()  # fetches

            for f in glob.glob(d + "/g2/process_0/*.bin") + \
                    glob.glob(d + "/g1/process_0/*.bin"):
                os.unlink(f)
            out = load_checkpoint(
                d + "/g2", t2, stats=(st := {}), resident=False, peers=src1
            )
            assert_trees_equal(out, t2)
            assert st.get("bytes_peer", 0) > 0
            assert src0.stats["bytes_served"] == st["bytes_peer"]
        finally:
            for h in (src0, src1):
                if h is not None:
                    h.close()
            ck.close()
            ex0.close()
            ex1.close()
            c0.close()
            c1.close()


# -- digest/crc disagreement: detected, quarantined, never committed ---------


class TestDigestDisagreement:
    def test_lying_device_verdict_fails_closed(self, tmp_path, monkeypatch):
        d = str(tmp_path)
        ck = AsyncCheckpointer(delta=True, digest=True, device_digest=True)
        try:
            t1 = _big_tree()
            ck.save(t1, d + "/g1", {"iteration": 1})

            # inject the fault AFTER the baseline exists: the device claims
            # every chunk unchanged while the staged bytes really changed —
            # the model of a torn D2H / stale staging buffer
            def lying_verdict(self, key, nbytes, fp):
                grid = writer_mod.chunk_grid(
                    nbytes, self.chunk_bytes, self.use_direct
                )
                return None, list(grid)

            monkeypatch.setattr(dd.DigestContext, "verdict", lying_verdict)
            t2 = _big_tree(mutate=("f0",))
            with pytest.raises(CheckpointSaveError):
                ck.save(t2, d + "/g2", {"iteration": 2})
        finally:
            with contextlib.suppress(Exception):
                ck.close()
        # the disagreeing shard is quarantined for post-mortem, and the
        # generation never commits (no merged metadata)
        assert glob.glob(d + "/g2/process_0/*.corrupt")
        assert not os.path.exists(d + "/g2/metadata.json")


# -- double-buffered snapshot ring -------------------------------------------


class TestSnapshotRing:
    def test_slow_drain_never_reuses_a_live_slot(self, tmp_path, monkeypatch):
        """Inject a slow D2H: with staging stalled, a rapid second save must
        take a FRESH buffer set (the fence holds); once drained, the next
        save donates a slot. Every generation restores byte-identically —
        the second snapshot never clobbered the first's device buffers."""
        real_stage = ckpt_mod.stage_pytree
        release = threading.Event()

        def slow_stage(*a, **kw):
            release.wait(timeout=30.0)  # D2H stalled until the test says go
            return real_stage(*a, **kw)

        monkeypatch.setattr(ckpt_mod, "stage_pytree", slow_stage)
        d = str(tmp_path)
        ck = AsyncCheckpointer(digest=True, stage_mode="snapshot",
                               stage_buffers=2)
        try:
            trees = [
                {"w": jnp.full((512,), float(i), jnp.float32),
                 "b": jnp.arange(64, dtype=jnp.int32) + i}
                for i in range(3)
            ]
            ck.async_save(trees[0], d + "/g0", {"iteration": 0})
            ck.async_save(trees[1], d + "/g1", {"iteration": 1})
            # both issued while staging was stalled: no slot was donatable
            assert ck.snap_ring_stats == {"reused": 0, "fresh": 2}
            release.set()
            ck.finalize_all()
            ck.async_save(trees[2], d + "/g2", {"iteration": 2})
            ck.finalize_all()
            # drained ring: the third save donated a slot instead
            assert ck.snap_ring_stats["reused"] == 1
        finally:
            release.set()
            ck.close()
        resident_mod.invalidate()
        for i in range(3):
            out = load_checkpoint(d + f"/g{i}", trees[0], resident=False)
            assert_trees_equal(out, trees[i])

    def test_ring_depth_one_is_legacy_snapshot(self, tmp_path):
        d = str(tmp_path)
        ck = AsyncCheckpointer(stage_mode="snapshot", stage_buffers=1)
        try:
            t = {"w": jnp.ones(256, jnp.float32)}
            ck.save(t, d + "/g1", {"iteration": 1})
            assert ck.snap_ring_stats == {"reused": 0, "fresh": 0}
        finally:
            ck.close()
        resident_mod.invalidate()
        assert_trees_equal(load_checkpoint(d + "/g1", t, resident=False), t)


# -- sharding-derived save planning ------------------------------------------


class _FakeDev:
    def __init__(self, id):  # noqa: A002 - mirrors jax.Device.id
        self.id = id


class _FakeSharding:
    def __init__(self, dmap):
        self._dmap = dmap

    def devices_indices_map(self, shape):
        return self._dmap


class _FakeLeaf:
    def __init__(self, shape, dmap):
        self.shape = shape
        self.sharding = _FakeSharding(dmap)


class TestShardOwnerMap:
    def _mesh(self):
        return Mesh(np.array(jax.devices()).reshape(4, 2), ("x", "y"))

    @pytest.mark.parametrize(
        "spec,n_boxes",
        [(P("x", "y"), 8), (P("x", None), 4), (P(None, "y"), 2), (P(), 1)],
    )
    def test_exactly_once_on_real_mesh(self, spec, n_boxes):
        """Each distinct index box gets ONE owner; summing shard_is_owner
        over all addressable shards equals the box count — exactly-once
        coverage, no replicated-leaf double-drain."""
        mesh = self._mesh()
        leaf = jax.device_put(
            np.arange(64 * 32, dtype=np.float32).reshape(64, 32),
            NamedSharding(mesh, spec),
        )
        owners = staging_mod.shard_owner_map(leaf)
        assert owners is not None and len(owners) == n_boxes
        owned = sum(
            staging_mod.shard_is_owner(leaf, s, 0, owners)
            for s in leaf.addressable_shards
        )
        assert owned == n_boxes
        total = sum(staging_mod._box_volume(b) for b in owners)
        assert total == 64 * 32

    def test_two_host_mesh_single_owner_per_box(self):
        """Simulated 2-host mesh: rows replicated across hosts — the owner
        map picks the lowest device id per box, so each host's planner
        derives the same assignment with no exchange."""
        sl = slice(None)
        dmap = {
            _FakeDev(0): (slice(0, 8), sl),   # host 0
            _FakeDev(4): (slice(0, 8), sl),   # host 1 replica
            _FakeDev(1): (slice(8, 16), sl),  # host 0
            _FakeDev(5): (slice(8, 16), sl),  # host 1 replica
        }
        owners = staging_mod.shard_owner_map(_FakeLeaf((16, 4), dmap))
        assert len(owners) == 2
        assert sorted(d.id for d in owners.values()) == [0, 1]

    def test_overlapping_boxes_rejected(self):
        sl = slice(None)
        dmap = {
            _FakeDev(0): (slice(0, 10), sl),
            _FakeDev(1): (slice(8, 16), sl),  # rows 8..10 double-drained
        }
        with pytest.raises(ValueError, match="exactly once"):
            staging_mod.shard_owner_map(_FakeLeaf((16, 4), dmap))

    def test_gapped_boxes_rejected(self):
        sl = slice(None)
        dmap = {
            _FakeDev(0): (slice(0, 8), sl),
            _FakeDev(1): (slice(8, 12), sl),  # rows 12..16 lost
        }
        with pytest.raises(ValueError, match="exactly once"):
            staging_mod.shard_owner_map(_FakeLeaf((16, 4), dmap))

    def test_host_arrays_fall_back(self):
        assert staging_mod.shard_owner_map(np.ones(8)) is None


# -- drain_progress under delta skips ----------------------------------------


class TestDrainProgressCredit:
    def test_skipped_bytes_credit_immediately(self, tmp_path):
        """Satellite 2: a provenance-only payload's bytes count toward
        drain progress the moment the plan sees it — NOT when a pool
        thread finishes, so a mostly-frozen delta save never reads as
        stalled below 100%."""
        seen = []
        nbytes = 256 * 1024
        eng = writer_mod._WriteEngine(
            str(tmp_path), 0, 2, "s1", "sigX",
            progress_cb=lambda w, t: seen.append((w, t)), digest=True,
        )
        eng.announce_total(nbytes)
        eng.add_payload({
            "leaf_idx": 0, "shard_idx": 0,
            "global_shape": [nbytes // 4], "index": [[0, nbytes // 4]],
            "dtype": "float32", "shm_name": "", "shape": [nbytes // 4],
            "nbytes": nbytes,
            "skip_spans": [[0, nbytes, 123, "/base/g0/process_0/s0.bin"]],
        })
        # credited at enqueue: the LAST report already shows full coverage,
        # before finish() waits on the pool at all
        assert seen and seen[-1] == (nbytes, nbytes)
        stats = eng.finish()
        assert stats["d2h_skipped_bytes"] == nbytes
        assert stats["bytes_written"] == 0
