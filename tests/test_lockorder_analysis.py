"""Whole-program analysis tier: call graph, lock-order rule (TPURX011),
and the runtime-witness confirm/prune round-trip.

Fixture trees mirror the repo layout under tmp_path because every rule
scopes by repo-relative path.  The fixture set follows the PR checklist:
a 2-lock cycle across two modules, RLock reentrancy (no finding),
Condition-under-lock, a lock handed through a helper function, and a
witness-file confirm/prune round trip.
"""

import json
import textwrap

from tpurx_lint import run_lint
from tpurx_lint.callgraph import CallGraph
from tpurx_lint.engine import parse_project

# -- shared fixture: a 2-lock cycle ACROSS two modules, with the back
# reference flowing through a constructor parameter (the realistic shape) --

MOD_A = """
    import threading
    from tpu_resiliency.b import Worker

    class Coordinator:
        def __init__(self):
            self._lock = threading.Lock()
            self.worker = Worker(self)

        def submit(self):
            with self._lock:
                self.worker.push()

        def poke(self):
            with self._lock:
                pass
"""

MOD_B = """
    import threading

    class Worker:
        def __init__(self, coord):
            self._cv = threading.Condition()
            self.coord = coord

        def push(self):
            with self._cv:
                pass

        def drain(self):
            with self._cv:
                self.coord.poke()
"""


def write_tree(tmp_path, files):
    for rel, code in files:
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(code))


def lint(tmp_path, rule="TPURX011", witness=None):
    result = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                      use_baseline=False, rule_ids=[rule],
                      witness_path=witness)
    return result


class TestCallGraph:
    def _graph(self, tmp_path, files):
        write_tree(tmp_path, files)
        project, errors = parse_project([str(tmp_path)], str(tmp_path))
        assert not errors
        return CallGraph.build(project)

    def test_cross_module_resolution_and_lock_table(self, tmp_path):
        cg = self._graph(tmp_path, [("tpu_resiliency/a.py", MOD_A),
                                    ("tpu_resiliency/b.py", MOD_B)])
        # symbol table
        assert "tpu_resiliency.a.Coordinator.submit" in cg.functions
        assert "tpu_resiliency.b.Worker.push" in cg.functions
        # cross-module call edge via inferred attribute type
        callees = {c for c, _l, _s in
                   cg.callees("tpu_resiliency.a.Coordinator.submit")}
        assert "tpu_resiliency.b.Worker.push" in callees
        # constructor-param propagation: Worker.coord picked up Coordinator
        back = {c for c, _l, _s in
                cg.callees("tpu_resiliency.b.Worker.drain")}
        assert "tpu_resiliency.a.Coordinator.poke" in back
        # lock table: identity, kind, declaration site
        lk = cg.locks["tpu_resiliency.a.Coordinator._lock"]
        assert lk.kind == "Lock" and lk.rel == "tpu_resiliency/a.py"
        cv = cg.locks["tpu_resiliency.b.Worker._cv"]
        assert cv.kind == "Condition" and cv.reentrant

    def test_condition_over_existing_lock_aliases(self, tmp_path):
        cg = self._graph(tmp_path, [("tpu_resiliency/m.py", """
            import threading

            class C:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._cv = threading.Condition(self._mu)
        """)])
        decl = cg.lookup_lock("tpu_resiliency.m.C", "_cv")
        # Condition(self._mu) IS self._mu for ordering purposes
        assert decl.attr == "_mu" and decl.kind == "Lock"

    def test_closure_is_bounded_on_recursion(self, tmp_path):
        cg = self._graph(tmp_path, [("tpu_resiliency/m.py", """
            def a():
                b()

            def b():
                a()
        """)])
        clo = cg.closure("tpu_resiliency.m.a")
        assert clo == {"tpu_resiliency.m.a", "tpu_resiliency.m.b"}


class TestLockOrderDeep:
    def test_two_lock_cycle_across_modules(self, tmp_path):
        write_tree(tmp_path, [("tpu_resiliency/a.py", MOD_A),
                              ("tpu_resiliency/b.py", MOD_B)])
        fs = lint(tmp_path).findings
        assert len(fs) == 1
        msg = fs[0].message
        assert "[PLAUSIBLE]" in msg
        assert "Coordinator._lock" in msg and "Worker._cv" in msg
        # both witness paths are in the report
        assert msg.count("acquire tpu_resiliency.a.Coordinator._lock") >= 1
        assert msg.count("acquire tpu_resiliency.b.Worker._cv") >= 1

    def test_rlock_reentrancy_no_finding(self, tmp_path):
        write_tree(tmp_path, [("tpu_resiliency/m.py", """
            import threading

            class R:
                def __init__(self):
                    self._mu = threading.RLock()

                def outer(self):
                    with self._mu:
                        self.inner()

                def inner(self):
                    with self._mu:
                        pass
        """)])
        assert not lint(tmp_path).findings

    def test_lock_self_deadlock_is_definite(self, tmp_path):
        write_tree(tmp_path, [("tpu_resiliency/m.py", """
            import threading

            class D:
                def __init__(self):
                    self._mu = threading.Lock()

                def outer(self):
                    with self._mu:
                        self.inner()

                def inner(self):
                    with self._mu:
                        pass
        """)])
        fs = lint(tmp_path).findings
        assert len(fs) == 1
        assert "self-deadlock" in fs[0].message

    def test_condition_under_lock_cycle(self, tmp_path):
        write_tree(tmp_path, [("tpu_resiliency/m.py", """
            import threading

            class C:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._cv = threading.Condition()

                def a(self):
                    with self._mu:
                        with self._cv:
                            pass

                def b(self):
                    with self._cv:
                        with self._mu:
                            pass
        """)])
        fs = lint(tmp_path).findings
        assert len(fs) == 1 and "deadlock" in fs[0].message

    def test_lock_handed_through_helper(self, tmp_path):
        write_tree(tmp_path, [("tpu_resiliency/m.py", """
            import threading

            def locked_call(lk, fn):
                with lk:
                    return fn()

            class H:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        locked_call(self._b, list)

                def two(self):
                    with self._b:
                        locked_call(self._a, list)
        """)])
        fs = lint(tmp_path).findings
        assert len(fs) == 1
        assert "hands" in fs[0].message

    def test_consistent_order_through_helper_passes(self, tmp_path):
        write_tree(tmp_path, [("tpu_resiliency/m.py", """
            import threading

            def locked_call(lk, fn):
                with lk:
                    return fn()

            class H:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        locked_call(self._b, list)

                def two(self):
                    with self._a:
                        locked_call(self._b, list)
        """)])
        assert not lint(tmp_path).findings


class TestWitnessRoundTrip:
    """Witness edges are keyed by lock CREATION sites — line numbers of the
    `self._x = threading.Lock()` declarations in the fixture modules."""

    LOCK_SITE = "tpu_resiliency/a.py:7"    # Coordinator._lock decl
    CV_SITE = "tpu_resiliency/b.py:6"      # Worker._cv decl

    def _witness(self, tmp_path, edges):
        wit = tmp_path / "witness.jsonl"
        with open(wit, "w") as f:
            f.write(json.dumps({"event": "meta", "pid": 1, "version": 1}) + "\n")
            for a, b in edges:
                f.write(json.dumps({
                    "event": "edge",
                    "frm": {"site": a, "kind": "Lock"},
                    "to": {"site": b, "kind": "Lock"},
                    "thread": "t",
                }) + "\n")
        return str(wit)

    def test_both_orders_observed_confirms(self, tmp_path):
        write_tree(tmp_path, [("tpu_resiliency/a.py", MOD_A),
                              ("tpu_resiliency/b.py", MOD_B)])
        wit = self._witness(tmp_path, [
            (self.LOCK_SITE, self.CV_SITE),
            (self.CV_SITE, self.LOCK_SITE),
        ])
        result = lint(tmp_path, witness=wit)
        assert len(result.findings) == 1
        assert "[CONFIRMED]" in result.findings[0].message
        assert not result.witness_pruned

    def test_consistent_runtime_order_prunes(self, tmp_path):
        write_tree(tmp_path, [("tpu_resiliency/a.py", MOD_A),
                              ("tpu_resiliency/b.py", MOD_B)])
        # runtime only ever took _lock before _cv: the reverse static path
        # never happens in practice -> pruned as a false positive
        wit = self._witness(tmp_path, [(self.LOCK_SITE, self.CV_SITE)])
        result = lint(tmp_path, witness=wit)
        assert not result.findings
        assert len(result.witness_pruned) == 1
        assert "[PRUNED]" in result.witness_pruned[0].message

    def test_unexercised_locks_stay_plausible(self, tmp_path):
        write_tree(tmp_path, [("tpu_resiliency/a.py", MOD_A),
                              ("tpu_resiliency/b.py", MOD_B)])
        wit = self._witness(tmp_path, [
            ("tpu_resiliency/other.py:1", "tpu_resiliency/other.py:2")])
        result = lint(tmp_path, witness=wit)
        assert len(result.findings) == 1
        assert "[PLAUSIBLE]" in result.findings[0].message

    def test_absolute_witness_paths_normalize(self, tmp_path):
        write_tree(tmp_path, [("tpu_resiliency/a.py", MOD_A),
                              ("tpu_resiliency/b.py", MOD_B)])
        abs_lock = str(tmp_path / "tpu_resiliency" / "a.py") + ":7"
        abs_cv = str(tmp_path / "tpu_resiliency" / "b.py") + ":6"
        wit = self._witness(tmp_path, [(abs_lock, abs_cv),
                                       (abs_cv, abs_lock)])
        result = lint(tmp_path, witness=wit)
        assert len(result.findings) == 1
        assert "[CONFIRMED]" in result.findings[0].message
