"""Scale smoke: the control-plane protocol at 64 concurrent agents.

The full 64/128/256 sweep lives in ``benchmarks/bench_control_plane.py``
(results in ``docs/SCALE.md``); this keeps the 64-agent path green in CI.
"""

from benchmarks.bench_control_plane import (
    bench_barrier,
    bench_consensus,
    bench_rendezvous,
)


def test_rendezvous_64_agents(store_server):
    out = bench_rendezvous(store_server.port, 64)
    assert out["round_close_s"] < 30.0
    assert out["result_fanout_s"] < 30.0


def test_barrier_and_consensus_64_agents(store_server):
    assert bench_barrier(store_server.port, 64)["barrier_fanin_s"] < 30.0
    out = bench_consensus(store_server.port, 64, calls=2)
    assert out["consensus_per_call_s"] < 15.0
