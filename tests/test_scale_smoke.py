"""Scale smoke: the control-plane protocol at 64 concurrent agents.

The full 64/128/256 sweep lives in ``benchmarks/bench_control_plane.py``
(results in ``docs/SCALE.md``); this keeps the 64-agent path green in CI.

Bounds are ~5-10x the measured numbers in docs/SCALE.md (round close
0.23s, barrier fan-in 0.013s, consensus 0.011s/call) — loose enough for a
loaded CI host, tight enough that an order-of-magnitude regression fails.
"""

from benchmarks.bench_control_plane import (
    bench_barrier,
    bench_consensus,
    bench_rendezvous,
)


def test_rendezvous_64_agents(store_server):
    out = bench_rendezvous(store_server.port, 64)
    assert out["round_close_s"] < 2.0    # measured 0.23s
    assert out["result_fanout_s"] < 2.0  # measured 0.24s


def test_barrier_and_consensus_64_agents(store_server):
    assert bench_barrier(store_server.port, 64)["barrier_fanin_s"] < 0.5  # 0.013s
    out = bench_consensus(store_server.port, 64, calls=2)
    assert out["consensus_per_call_s"] < 0.5  # measured 0.011s/call
