"""Health checks, profiling recorder, log config tests (previously indirect)."""

import json
import logging
import os
import time

import pytest

from tpu_resiliency.health import (
    ChainedHealthCheck,
    DeviceHealthCheck,
    HealthCheck,
    HealthCheckResult,
    NicLinkHealthCheck,
    NodeResourceHealthCheck,
    StoragePathHealthCheck,
)
from tpu_resiliency.utils.profiling import ProfilingEvent, ProfilingRecorder


class _Fail(HealthCheck):
    name = "always_fail"

    def _check(self):
        return HealthCheckResult(False, "nope")


class _Pass(HealthCheck):
    name = "always_pass"

    def _check(self):
        return HealthCheckResult(True, "fine")


class _Boom(HealthCheck):
    name = "crasher"

    def _check(self):
        raise RuntimeError("check exploded")


class TestHealthChecks:
    def test_chained_fail_fast(self):
        result = ChainedHealthCheck([_Pass(), _Fail(), _Pass()]).run()
        assert not result.healthy
        assert result.name == "always_fail"

    def test_chained_collect_all(self):
        result = ChainedHealthCheck([_Fail(), _Boom()], fail_fast=False).run()
        assert not result.healthy
        assert "always_fail" in result.message and "crasher" in result.message

    def test_crashing_check_is_unhealthy(self):
        result = _Boom().run()
        assert not result.healthy
        assert "check exploded" in result.message
        assert result.duration_s >= 0

    def test_node_resources_ok_by_default(self):
        assert NodeResourceHealthCheck().run().healthy

    def test_node_resources_disk_threshold(self, tmp_path):
        result = NodeResourceHealthCheck(
            min_free_disk_mb=10 ** 9, disk_path=str(tmp_path)
        ).run()
        assert not result.healthy
        assert "low disk" in result.message

    def test_storage_probe_roundtrip(self, tmp_path):
        result = StoragePathHealthCheck(str(tmp_path)).run()
        assert result.healthy
        # no probe files left behind
        assert not list(tmp_path.iterdir())

    def test_storage_probe_unwritable(self, tmp_path):
        # a regular file as path parent fails regardless of uid (root
        # ignores permission bits, so chmod-based denial would not)
        blocker = tmp_path / "file"
        blocker.write_text("x")
        result = StoragePathHealthCheck(str(blocker / "sub")).run()
        assert not result.healthy

    def test_nic_link_check_with_fake_sysfs(self, tmp_path):
        for iface, state in (("eth0", "up"), ("eth1", "down")):
            d = tmp_path / iface
            d.mkdir()
            (d / "operstate").write_text(state + "\n")
        ok = NicLinkHealthCheck(["eth0"], sys_net=str(tmp_path)).run()
        assert ok.healthy
        bad = NicLinkHealthCheck(sys_net=str(tmp_path)).run()
        assert not bad.healthy
        assert "eth1=down" in bad.message

    def test_device_probe_via_subprocess(self):
        DeviceHealthCheck.clear_cache()
        result = DeviceHealthCheck(
            timeout=120, env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""}
        ).run()
        assert result.healthy, result.message
        # cached on second run
        again = DeviceHealthCheck(timeout=1).run()
        assert again.healthy and "cached" in again.message
        DeviceHealthCheck.clear_cache()


class TestProfilingRecorder:
    def test_records_and_latency(self, tmp_path):
        path = str(tmp_path / "prof.jsonl")
        rec = ProfilingRecorder(path=path, cycle=2)
        rec.record(ProfilingEvent.FAILURE_DETECTED, rank=3)
        time.sleep(0.01)
        rec.record(ProfilingEvent.WORKER_STARTED)
        lat = rec.latency_ns(ProfilingEvent.FAILURE_DETECTED, ProfilingEvent.WORKER_STARTED)
        assert lat is not None and lat > 0
        lines = [json.loads(l) for l in open(path)]
        assert lines[0]["event"] == "_flight_meta"  # alignment header
        assert lines[1]["event"] == "failure_detected"
        assert lines[1]["cycle"] == 2
        assert lines[1]["rank"] == 3

    def test_latency_none_when_missing(self):
        rec = ProfilingRecorder()
        assert rec.latency_ns(ProfilingEvent.FAILURE_DETECTED, ProfilingEvent.WORKER_STARTED) is None


def test_log_funnel_gap_detection(tmp_path):
    """A skipped batch sequence is surfaced in the aggregate log."""
    import socket
    import struct

    from tpu_resiliency.utils.log_funnel import RootLogServer

    root = RootLogServer(str(tmp_path / "agg.log"), host="127.0.0.1", flush_age=0.05)
    U32 = struct.Struct("<I")

    def send(batch):
        raw = json.dumps(batch).encode()
        s = socket.create_connection(("127.0.0.1", root.port))
        s.sendall(U32.pack(len(raw)) + raw)
        s.close()

    send({"source": "n1", "seq": 1, "lines": ["a"]})
    send({"source": "n1", "seq": 4, "lines": ["b"], "dropped": 2})
    time.sleep(0.4)
    root.close()
    content = (tmp_path / "agg.log").read_text()
    assert "[n1] a" in content and "[n1] b" in content
    assert "GAP from n1" in content
    assert "dropped 2 lines" in content


def test_shm_janitor_removes_only_orphans(tmp_path, monkeypatch):
    from multiprocessing import shared_memory

    import tpu_resiliency.utils.shm_janitor as sj

    # held segment: must survive; orphan: must be removed (age forced)
    held = shared_memory.SharedMemory(create=True, size=1024)
    orphan = shared_memory.SharedMemory(create=True, size=1024)
    orphan_name = orphan.name
    orphan.close()  # unmapped by everyone, but still linked in /dev/shm
    try:
        ours = {held.name.lstrip("/"), orphan_name.lstrip("/")}
        monkeypatch.setattr(
            sj, "_age",
            lambda path: 10_000.0 if path.rsplit("/", 1)[1] in ours else 0.0,
        )
        removed = sj.sweep(min_age_s=600.0)
        assert orphan_name.lstrip("/") in [r.lstrip("/") for r in removed]
        assert held.name.lstrip("/") not in [r.lstrip("/") for r in removed]
        # held segment still usable
        held.buf[0] = 7
        assert held.buf[0] == 7
    finally:
        held.close()
        held.unlink()
        try:
            shared_memory.SharedMemory(name=orphan_name).unlink()
        except FileNotFoundError:
            pass


class TestConfig:
    def test_yaml_section_discovery_nested(self, tmp_path):
        from tpu_resiliency.fault_tolerance.config import FaultToleranceConfig

        # the section hides inside an arbitrary trainer config tree
        (tmp_path / "trainer.yaml").write_text(
            "trainer:\n"
            "  devices: 8\n"
            "  plugins:\n"
            "    fault_tolerance:\n"
            "      rank_heartbeat_timeout: 120.5\n"
            "      max_nodes: 4\n"
            "      rank_section_timeouts: {step: 60}\n"
        )
        cfg = FaultToleranceConfig.from_yaml(str(tmp_path / "trainer.yaml"))
        assert cfg.rank_heartbeat_timeout == 120.5
        assert cfg.max_nodes == 4
        assert cfg.rank_section_timeouts == {"step": 60}

    def test_yaml_missing_section(self, tmp_path):
        from tpu_resiliency.fault_tolerance.config import FaultToleranceConfig

        (tmp_path / "c.yaml").write_text("foo: {bar: 1}\n")
        with pytest.raises(ValueError, match="not found"):
            FaultToleranceConfig.from_yaml(str(tmp_path / "c.yaml"))

    def test_unknown_key_rejected(self):
        from tpu_resiliency.fault_tolerance.config import FaultToleranceConfig

        with pytest.raises(ValueError, match="unknown"):
            FaultToleranceConfig.from_dict({"not_a_real_field": 1})

    def test_env_null_disables_timeout(self, monkeypatch):
        from tpu_resiliency.fault_tolerance.config import FaultToleranceConfig

        monkeypatch.setenv("TPURX_FT_RANK_HEARTBEAT_TIMEOUT", "null")
        cfg = FaultToleranceConfig().merged_with_env()
        assert cfg.rank_heartbeat_timeout is None


class TestDataModel:
    def test_timeouts_json_roundtrip(self):
        from tpu_resiliency.fault_tolerance.data import (
            HeartbeatTimeouts,
            SectionTimeouts,
            heartbeat_timeouts_from_dict,
            heartbeat_timeouts_to_dict,
            section_timeouts_from_dict,
            section_timeouts_to_dict,
        )

        hb = HeartbeatTimeouts(initial=10.0, subsequent=None, were_calculated=True)
        assert heartbeat_timeouts_from_dict(heartbeat_timeouts_to_dict(hb)) == hb
        st = SectionTimeouts(
            section={"step": 5.0, "ckpt": None}, out_of_section=9.0,
            calculated_sections=("step",), calculated_out_of_section=True,
        )
        back = section_timeouts_from_dict(section_timeouts_to_dict(st))
        assert back.section == st.section
        assert back.out_of_section == st.out_of_section
        assert back.calculated_sections == st.calculated_sections

    def test_workload_control_roundtrip(self):
        from tpu_resiliency.fault_tolerance.data import (
            WorkloadAction,
            WorkloadControlRequest,
        )

        req = WorkloadControlRequest(WorkloadAction.ExcludeThisNode, "bad hbm")
        back = WorkloadControlRequest.from_json(req.to_json())
        assert back.action == WorkloadAction.ExcludeThisNode
        assert back.reason == "bad hbm"


def test_cycle_log_router_caps_file_size(tmp_path):
    import os

    from tpu_resiliency.fault_tolerance.per_cycle_logs import CycleLogRouter

    router = CycleLogRouter(str(tmp_path), tee_to_stdout=False,
                            max_bytes_per_cycle=200)
    router.start_cycle(0)
    w_fd = router.make_worker_pipe(0, "out")
    with os.fdopen(w_fd, "w") as wf:
        for i in range(100):
            wf.write(f"spam line {i}\n")
    time.sleep(0.3)
    router.close()
    content = (tmp_path / "cycle_0.log").read_text()
    assert "TRUNCATED" in content
    assert len(content) < 1000  # capped, not 100 lines
    assert "spam line 0" in content
