"""Store-key lifecycle fixes driven by lint rule TPURX013, plus the
bounded background-save join (TPURX012 burndown).

The leaks these pin down: per-iteration in-process protocol keys
(interruption/fingerprint logs, completion markers, iteration barriers)
and per-generation ICI-replication blob rows accumulated in the
control-plane store for the life of the job — O(restarts) and O(rounds)
growth that a 10k-rank job turns into a store OOM.
"""

import threading
import time

import pytest

from tpu_resiliency.inprocess.store_ops import InprocStore
from tpu_resiliency.store.barrier import (
    barrier, barrier_keys, gc_barrier, reentrant_barrier,
)
from tpu_resiliency.store.client import StoreTimeout


class FakeStore:
    """Dict-backed stand-in implementing the KV surface the protocol uses."""

    def __init__(self):
        self.kv = {}

    @staticmethod
    def _b(value):
        return value if isinstance(value, bytes) else str(value).encode()

    def set(self, key, value):
        self.kv[key] = self._b(value)

    def append(self, key, value):
        self.kv[key] = self.kv.get(key, b"") + self._b(value)
        return len(self.kv[key])

    def add(self, key, amount):
        cur = int(self.kv.get(key, b"0"))
        cur += amount
        self.kv[key] = str(cur).encode()
        return cur

    def get(self, key, timeout=None):
        return self.kv[key]

    def try_get(self, key):
        return self.kv.get(key)

    def check(self, keys):
        return all(k in self.kv for k in keys)

    def wait(self, keys, timeout=None):
        if not self.check(keys):
            raise StoreTimeout(f"missing {keys}")

    def delete(self, key):
        return self.kv.pop(key, None) is not None


class TestBarrierGC:
    def test_barrier_keys_cover_both_flavors(self):
        ks = barrier_keys("x/b", generation=0)
        assert "barrier/x/b/count" in ks
        assert "barrier/x/b/arrivals" in ks
        assert "barrier/x/b/done" in ks

    def test_gc_barrier_removes_counting_barrier_keys(self):
        store = FakeStore()
        barrier(store, "r/b", world_size=1, timeout=1.0)
        assert any(k.startswith("barrier/r/b") for k in store.kv)
        gc_barrier(store, "r/b")
        assert not any(k.startswith("barrier/r/b") for k in store.kv)

    def test_gc_barrier_removes_reentrant_keys_per_generation(self):
        store = FakeStore()
        reentrant_barrier(store, "it/b", rank=0, world_size=1,
                          timeout=1.0, generation=3)
        assert any("/g3/" in k for k in store.kv)
        gc_barrier(store, "it/b", generation=3)
        assert not store.kv

    def test_gc_is_idempotent(self):
        store = FakeStore()
        gc_barrier(store, "never/ran")   # no keys: no error


class TestIterationKeyGC:
    def _populate(self, ops, iteration):
        from tpu_resiliency.inprocess.attribution import (
            Interruption, InterruptionRecord,
        )
        ops.record_interruption(iteration, InterruptionRecord(
            rank=0, interruption=Interruption.EXCEPTION, message="x"))
        ops.record_fingerprint(iteration, 0, [("op", 1)])
        ops.mark_completed(iteration)
        ops.iteration_barrier(iteration, 0, [0], timeout=1.0)

    def test_gc_iteration_removes_all_round_keys(self):
        store = FakeStore()
        ops = InprocStore(store)
        self._populate(ops, 0)
        self._populate(ops, 1)
        n_before = len(store.kv)
        assert n_before > 0
        ops.gc_iteration(0)
        # every iter-0 key gone, every iter-1 key intact
        assert not [k for k in store.kv if "/iter/0/" in k], store.kv
        assert [k for k in store.kv if "/iter/1/" in k]
        # protocol reads degrade to empty, not errors
        assert ops.get_interruptions(0) == []
        assert not ops.get_fingerprints(0)
        assert not ops.any_completed(0)

    def test_gc_iteration_negative_is_noop(self):
        store = FakeStore()
        ops = InprocStore(store)
        ops.gc_iteration(-1)
        ops.gc_iteration(-2)
        assert not store.kv

    def test_durable_keys_survive_gc(self):
        store = FakeStore()
        ops = InprocStore(store)
        ops.mark_terminated(3)
        ops.heartbeat(0)
        self._populate(ops, 0)
        ops.gc_iteration(0)
        assert ops.terminated_ranks() == [3]
        assert ops.last_heartbeat(0) is not None


class TestIciReplicationGC:
    def test_gen2_blob_rows_and_barrier_are_collected(self):
        from tpu_resiliency.checkpointing.local.ici_replication import (
            IciReplication,
        )
        import numpy as np

        store = FakeStore()
        rep = IciReplication.__new__(IciReplication)
        rep.store = store
        rep.rank = 0
        rep.world_size = 1
        rep._sync_gen = 0

        buf = np.zeros(16, dtype=np.uint8)
        buf[:8] = np.frombuffer(np.uint64(8).tobytes(), dtype=np.uint8)
        for _ in range(4):
            rep._assemble_single_process(buf, 16, None)
        live_gens = {
            k.split("/")[2] for k in store.kv
            if k.startswith("ici_repl/blob/")
        }
        # rounds 0 and 1 were GC'd when rounds 2 and 3 started
        assert "0" not in live_gens and "1" not in live_gens
        assert {"2", "3"} <= live_gens
        assert not [k for k in store.kv
                    if k.startswith("barrier/ici_repl/blob/0")]


class TestBoundedBackgroundSaveJoin:
    """TPURX012 burndown: a wedged background local save used to park every
    caller of manager.wait() forever; now it raises, naming the thread."""

    def _manager(self, tmp_path):
        from tpu_resiliency.checkpointing.local.manager import (
            LocalCheckpointManager,
        )
        return LocalCheckpointManager(
            root_dir=str(tmp_path), rank=0, world_size=1)

    def test_wedged_save_raises_instead_of_hanging(self, tmp_path):
        mgr = self._manager(tmp_path)
        release = threading.Event()
        mgr._bg = threading.Thread(
            target=release.wait, kwargs={"timeout": 30.0},
            name="wedged-save", daemon=True)
        mgr._bg.start()
        t0 = time.monotonic()
        with pytest.raises(TimeoutError, match="wedged-save"):
            mgr.wait(timeout=0.2)
        assert time.monotonic() - t0 < 5.0
        release.set()

    def test_completed_save_joins_and_surfaces_errors(self, tmp_path):
        mgr = self._manager(tmp_path)
        mgr._bg = threading.Thread(target=lambda: None, daemon=True)
        mgr._bg.start()
        mgr.wait(timeout=5.0)
        assert mgr._bg is None
        mgr._bg_error = ValueError("boom")
        with pytest.raises(RuntimeError, match="boom"):
            mgr.wait(timeout=5.0)
