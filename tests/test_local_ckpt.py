"""Local checkpointing tests (reference analog: tests/checkpointing/unit/test_basic_local.py,
test_cleanup.py + replication tests): multi-threaded "ranks" with real TCP
peer exchange and a real store."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_resiliency.checkpointing.local.manager import LocalCheckpointManager
from tpu_resiliency.checkpointing.local.replication import (
    CliqueReplication,
    PeerExchange,
    clique_members,
)
from tpu_resiliency.checkpointing.local.state_dict import TensorAwareTree
from tpu_resiliency.store import StoreClient


def make_tree(rank, seed=0):
    k = jax.random.PRNGKey(seed * 100 + rank)
    return {
        "w": jax.random.normal(k, (8, 4)),
        "step": np.int64(seed),
        "rank_marker": np.array([rank], dtype=np.int32),
    }


class TestTensorAwareTree:
    def test_pop_insert_roundtrip(self):
        tree = make_tree(0)
        tat = TensorAwareTree.from_tree(tree)
        arrays = tat.pop_tensors()
        assert tat.is_hollow
        with pytest.raises(RuntimeError):
            tat.pop_tensors()
        tat.insert_tensors(arrays)
        rebuilt = tat.to_tree(template=tree)
        np.testing.assert_array_equal(np.asarray(rebuilt["w"]), np.asarray(tree["w"]))
        assert isinstance(rebuilt["w"], jax.Array)

    def test_bytes_roundtrip(self):
        tree = make_tree(3, seed=9)
        blob = TensorAwareTree.from_tree(tree).to_bytes()
        back = TensorAwareTree.from_bytes(blob)
        rebuilt = back.to_tree_like(tree)
        np.testing.assert_array_equal(np.asarray(rebuilt["w"]), np.asarray(tree["w"]))
        assert rebuilt["rank_marker"][0] == 3

    def test_bad_magic(self):
        with pytest.raises(ValueError):
            TensorAwareTree.from_bytes(b"NOTMAGIC" + b"x" * 64)


class TestCliqueMembers:
    def test_contiguous(self):
        assert clique_members(0, 8, 2, 1) == [0, 1]
        assert clique_members(1, 8, 2, 1) == [0, 1]
        assert clique_members(5, 8, 2, 1) == [4, 5]

    def test_jump(self):
        # factor 2, jump 4 (e.g. 4 ranks per host): replicas on another host
        assert clique_members(0, 8, 2, 4) == [0, 4]
        assert clique_members(5, 8, 2, 4) == [1, 5]

    def test_no_replication(self):
        assert clique_members(3, 8, 1, 1) == [3]

    def test_truncated_tail(self):
        assert clique_members(6, 7, 2, 1) == [6]


def _run_ranks(world, fn):
    errors, results = [], {}

    def wrap(rank):
        try:
            results[rank] = fn(rank)
        except Exception as exc:  # noqa: BLE001
            import traceback

            traceback.print_exc()
            errors.append((rank, exc))

    threads = [threading.Thread(target=wrap, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    return results


def test_peer_exchange(store_server):
    world = 3

    def member(rank):
        store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
        ex = PeerExchange(store, rank, namespace="px1")
        try:
            ex.send((rank + 1) % world, tag=7, payload=f"hello-from-{rank}".encode())
            got = ex.recv((rank - 1) % world, tag=7, timeout=30.0)
            return got.decode()
        finally:
            ex.close()
            store.close()

    results = _run_ranks(world, member)
    for r in range(world):
        assert results[r] == f"hello-from-{(r - 1) % world}"


def test_save_load_with_replication(store_server, tmp_path):
    world, factor = 4, 2

    def member(rank):
        store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
        ex = PeerExchange(store, rank, namespace="px2")
        repl = CliqueReplication(ex, world, replication_factor=factor)
        mgr = LocalCheckpointManager(
            str(tmp_path / f"node{rank}"),  # separate dirs = separate "disks"
            rank, world, store=store, replication=repl,
        )
        try:
            mgr.save(make_tree(rank, seed=1), iteration=10, is_async=False)
            latest = mgr.find_latest()
            assert latest == 10
            tree, it = mgr.load(make_tree(rank), iteration=latest)
            return int(np.asarray(tree["rank_marker"])[0])
        finally:
            ex.close()
            store.close()

    results = _run_ranks(world, member)
    for r in range(world):
        assert results[r] == r  # every rank got ITS OWN data back


def test_load_after_node_loss(store_server, tmp_path):
    """Rank 1 loses its disk; its clique buddy (rank 0) serves the replica."""
    world, factor = 2, 2

    def phase1(rank):
        store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
        ex = PeerExchange(store, rank, namespace="px3a")
        repl = CliqueReplication(ex, world, replication_factor=factor)
        mgr = LocalCheckpointManager(
            str(tmp_path / f"node{rank}"), rank, world, store=store, replication=repl
        )
        try:
            mgr.save(make_tree(rank, seed=2), iteration=5, is_async=False)
        finally:
            ex.close()
            store.close()

    _run_ranks(world, phase1)

    # simulate node 1's disk loss
    import shutil

    shutil.rmtree(tmp_path / "node1")

    def phase2(rank):
        store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
        ex = PeerExchange(store, rank, namespace="px3b")
        repl = CliqueReplication(ex, world, replication_factor=factor)
        mgr = LocalCheckpointManager(
            str(tmp_path / f"node{rank}"), rank, world, store=store, replication=repl
        )
        try:
            latest = mgr.find_latest()
            assert latest == 5, f"rank {rank} found {latest}"
            tree, _ = mgr.load(make_tree(rank), iteration=latest)
            return int(np.asarray(tree["rank_marker"])[0])
        finally:
            ex.close()
            store.close()

    results = _run_ranks(world, phase2)
    assert results[1] == 1  # recovered its own data from rank 0's replica
    assert results[0] == 0


def test_cleanup_keeps_last(store_server, tmp_path):
    store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
    mgr = LocalCheckpointManager(
        str(tmp_path / "solo"), 0, 1, store=store, keep_last=2
    )
    for it in (1, 2, 3, 4):
        mgr.save(make_tree(0, seed=it), iteration=it, is_async=False)
    holdings = mgr._holdings()
    assert sorted(holdings) == [3, 4]
    assert mgr.find_latest() == 4
    store.close()


def test_async_local_save(store_server, tmp_path):
    store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
    mgr = LocalCheckpointManager(str(tmp_path / "a"), 0, 1, store=store)
    tree = make_tree(0, seed=7)
    mgr.save(tree, iteration=42, is_async=True)
    mgr.wait()
    loaded, it = mgr.load(tree)
    assert it == 42
    np.testing.assert_array_equal(np.asarray(loaded["w"]), np.asarray(tree["w"]))
    store.close()


def test_ici_replication_roundtrip(store_server):
    """ICI-path replication: blobs shifted over the mesh via ppermute; each
    rank ends up holding its jump-predecessor's blob."""
    import jax

    from tpu_resiliency.checkpointing.local.ici_replication import IciReplication
    from tpu_resiliency.parallel.mesh import make_mesh

    world = 8
    mesh = make_mesh(("data",), (world,))
    results = {}

    def member(rank):
        store = StoreClient("127.0.0.1", store_server.port, timeout=30.0)
        repl = IciReplication(
            mesh, store, rank, world, replication_factor=2, replication_jump=4
        )
        blob = f"state-of-rank-{rank}".encode() * (rank + 1)  # unequal lengths
        results[rank] = repl.replicate(blob, tag=7)
        store.close()

    errors = _run_ranks(world, member)
    for rank in range(world):
        got = results[rank]
        src = (rank - 4) % world
        assert got[rank] == f"state-of-rank-{rank}".encode() * (rank + 1)
        assert got[src] == f"state-of-rank-{src}".encode() * (src + 1)
        assert set(got) == {rank, src}


def test_ici_replication_in_manager(store_server, tmp_path):
    """LocalCheckpointManager with the ICI strategy for save-time replication."""
    import jax

    from tpu_resiliency.checkpointing.local.ici_replication import IciReplication
    from tpu_resiliency.parallel.mesh import make_mesh

    world = 2
    mesh = make_mesh(("data",), (-1,))
    # use a 2-wide submesh so axis == world
    mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])

    def member(rank):
        store = StoreClient("127.0.0.1", store_server.port, timeout=30.0)
        repl = IciReplication(mesh, store, rank, world, replication_factor=2)
        mgr = LocalCheckpointManager(
            str(tmp_path / f"n{rank}"), rank, world, store=store, replication=repl
        )
        mgr.save(make_tree(rank, seed=3), iteration=9, is_async=False)
        # replicas landed: each node dir holds both ranks' blobs
        holdings = mgr._holdings()
        assert holdings == {9: [0, 1]}, holdings
        store.close()
        return True

    results = _run_ranks(world, member)
    assert all(results.values())


def test_partial_blob_without_done_marker_ignored(store_server, tmp_path):
    """A save killed between blob write and .done marker must not count as a
    valid checkpoint (crash consistency of the local format)."""
    store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
    mgr = LocalCheckpointManager(str(tmp_path / "n"), 0, 1, store=store)
    mgr.save(make_tree(0, seed=1), iteration=3, is_async=False)
    # simulate a crash mid-save of iteration 4: blob present, no .done
    d = mgr._iter_dir(4)
    import os

    os.makedirs(d, exist_ok=True)
    with open(mgr._blob_path(4, 0), "wb") as f:
        f.write(b"partial garbage")
    assert mgr._holdings() == {3: [0]}
    assert mgr.find_latest() == 3
    tree, it = mgr.load(make_tree(0))
    assert it == 3
    store.close()


def test_cleanup_reclaims_crash_debris(store_server, tmp_path):
    """Uncommitted iter dirs older than a committed save are removed; a
    potentially in-progress (newest) uncommitted dir is left alone."""
    import os

    store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
    mgr = LocalCheckpointManager(str(tmp_path / "n"), 0, 1, store=store)
    # crash debris at iteration 1 (no .done)
    os.makedirs(mgr._iter_dir(1), exist_ok=True)
    with open(mgr._blob_path(1, 0), "wb") as f:
        f.write(b"junk")
    # newest uncommitted (could be an in-flight save) at iteration 9
    os.makedirs(mgr._iter_dir(9), exist_ok=True)
    with open(mgr._blob_path(9, 0), "wb") as f:
        f.write(b"in progress")
    mgr.save(make_tree(0, seed=2), iteration=5, is_async=False)  # runs cleanup
    assert not os.path.exists(mgr._iter_dir(1))   # debris reclaimed
    assert os.path.exists(mgr._iter_dir(9))       # in-progress spared
    assert mgr.find_latest() == 5
    store.close()


def test_ici_save_tcp_recovery_cross_transport(store_server, tmp_path):
    """The scenario that justifies the hybrid design: save over ICI
    (ppermute replication), LOSE one node's directory, and restore it from
    the clique buddy over the DCN TCP lane (IciReplication.execute_plan
    delegating to a lazily-built PeerExchange)."""
    import shutil

    from tpu_resiliency.checkpointing.local.ici_replication import IciReplication
    from tpu_resiliency.parallel.mesh import make_mesh

    world = 2
    lost_rank = 1
    mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])
    trees = {r: make_tree(r, seed=7) for r in range(world)}

    def save_rank(rank):
        store = StoreClient("127.0.0.1", store_server.port, timeout=30.0)
        repl = IciReplication(mesh, store, rank, world, replication_factor=2)
        mgr = LocalCheckpointManager(
            str(tmp_path / f"n{rank}"), rank, world, store=store,
            replication=repl,
        )
        mgr.save(trees[rank], iteration=4, is_async=False)
        repl.close()
        store.close()
        return True

    assert all(_run_ranks(world, save_rank).values())

    # node of lost_rank dies; its local checkpoints are gone
    shutil.rmtree(tmp_path / f"n{lost_rank}")

    def recover_rank(rank):
        store = StoreClient("127.0.0.1", store_server.port, timeout=30.0)
        repl = IciReplication(mesh, store, rank, world, replication_factor=2)
        mgr = LocalCheckpointManager(
            str(tmp_path / f"n{rank}"), rank, world, store=store,
            replication=repl,
        )
        latest = mgr.find_latest()
        assert latest == 4, latest
        tree, iteration = mgr.load(template=trees[rank], iteration=latest)
        repl.close()
        store.close()
        return tree, iteration

    results = _run_ranks(world, recover_rank)
    for rank in range(world):
        tree, iteration = results[rank]
        assert iteration == 4
        np.testing.assert_array_equal(
            np.asarray(tree["w"]), np.asarray(trees[rank]["w"])
        )
        assert tree["rank_marker"][0] == rank
