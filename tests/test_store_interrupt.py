"""Interrupt-at-every-point + brownout coverage for the store client.

The tentpole contract under test: NO store code path may sit in a single
C-level wait longer than the poll quantum (``TPURX_STORE_POLL_S``), so a
pending async raise (in-process restart abort, monitor-triggered teardown,
shutdown) lands between slices — never parked behind one uninterruptible
``recv``.  Each test parks a worker thread at a different point of the I/O
state machine (connect, send, recv-mid-frame, server-held long poll,
cross-shard fan-out, mux subscription), injects
``PyThreadState_SetAsyncExc`` and asserts the raise lands within the
contract budget AND the client is cleanly re-usable afterwards (no
half-read frames on the wire).

Brownout coverage: a server that accepts connections but never answers
(``TPURX_STORE_TEST_BROWNOUT``) must be escaped via the per-op first-byte
deadline (:class:`StoreBrownout`), retried on a sibling endpoint by the
failover client, and ridden out by the sharded client's existing
``store_shard_failover`` episode ending in spare promotion — never a hung
caller.
"""

import ctypes
import socket
import struct
import threading
import time

import pytest

from tpu_resiliency.store import (
    FailoverStoreClient,
    ShardMap,
    ShardServerGroup,
    ShardedStoreClient,
    StoreBrownout,
    StoreClient,
    StoreServer,
    spawn_shard_subprocess,
)
from tpu_resiliency.store.client import (
    StoreError,
    _brownout_grace,
    _poll_quantum,
)
from tpu_resiliency.store.mux import MuxStoreClient
from tpu_resiliency.store.sharding import free_port

# Small quantum so landing-latency assertions are tight; the contract is
# "within 2x the poll quantum", LAND_SLACK covers CI scheduler jitter and
# the cost of the BaseException cleanup path (socket close) on top.
QUANTUM = 0.05
LAND_SLACK = 1.5


@pytest.fixture(autouse=True)
def _fast_quantum(monkeypatch):
    monkeypatch.setenv("TPURX_STORE_POLL_S", str(QUANTUM))
    yield


class _Interrupt(Exception):
    """Stand-in for the restart/abort async raise."""


def _async_raise(tid: int) -> None:
    n = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(tid), ctypes.py_object(_Interrupt)
    )
    if n > 1:  # pragma: no cover - undo over-broad delivery
        ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(tid), None)
    assert n == 1, f"async raise delivered to {n} threads"


def _interrupt_parked(target, settle: float = 0.5, join: float = 20.0):
    """Run ``target`` in a thread, async-raise once it is parked, and
    return how long the raise took to LAND (from injection to the except
    block running)."""
    box = {}

    def run():
        try:
            box["ret"] = target()
        except _Interrupt:
            box["landed"] = time.monotonic()
        except BaseException as exc:  # noqa: BLE001 - surfaced in assert
            box["err"] = exc

    th = threading.Thread(target=run, daemon=True)
    th.start()
    time.sleep(settle)  # let target reach its blocking wait
    assert th.is_alive(), f"target finished before injection: {box}"
    t0 = time.monotonic()
    _async_raise(th.ident)
    th.join(timeout=join)
    assert not th.is_alive(), "interrupt never landed; thread still parked"
    assert "landed" in box, f"interrupt swallowed or transformed: {box}"
    return box["landed"] - t0


def _assert_landed(dt: float) -> None:
    assert dt <= 2 * QUANTUM + LAND_SLACK, (
        f"async raise took {dt:.2f}s to land; contract is ~2x quantum "
        f"({2 * QUANTUM:.2f}s) plus scheduling slack"
    )


@pytest.fixture
def server():
    srv = StoreServer(host="127.0.0.1", port=0).start_in_thread()
    yield srv
    srv.stop()


# -- async raise at every point of the I/O state machine ----------------------


class TestInterruptEveryPoint:
    def test_mid_long_poll_wait_lands_and_client_reusable(self, server):
        """The documented flake: a rank parked in wait() used to sit ~30s in
        one C-level recv, so the restart raise could not land.  Now every
        recv slice is one quantum long."""
        c = StoreClient("127.0.0.1", server.port, timeout=60.0)
        dt = _interrupt_parked(lambda: c.wait(["never/set"], timeout=60.0))
        _assert_landed(dt)
        # clean re-entry: the socket was dropped mid-frame, the next op
        # reconnects and runs normally — no half-read frame parsing
        assert c._sock is None
        c.set("after/interrupt", b"ok")
        assert c.get("after/interrupt", timeout=5.0) == b"ok"
        c.close()

    def test_mid_long_poll_get_lands(self, server):
        c = StoreClient("127.0.0.1", server.port, timeout=60.0)
        dt = _interrupt_parked(lambda: c.get("never/get", timeout=60.0))
        _assert_landed(dt)
        c.set("g", b"v")
        assert c.get("g", timeout=5.0) == b"v"
        c.close()

    def test_mid_recv_partial_frame_lands_and_drops_socket(self):
        """Server sends ONE byte of the response then stalls: the client is
        mid-frame in _read_exact.  The raise must land within a slice and
        the desynced socket must be dropped (never re-parsed)."""
        lst = socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(1)
        port = lst.getsockname()[1]
        stop = threading.Event()

        def stall_server():
            conn, _ = lst.accept()
            conn.recv(4096)  # the request frame
            conn.sendall(b"\x00")  # Status.OK ... and nothing else, ever
            stop.wait(30.0)
            conn.close()

        st = threading.Thread(target=stall_server, daemon=True)
        st.start()
        c = StoreClient("127.0.0.1", port, timeout=60.0, retries=0)
        try:
            dt = _interrupt_parked(
                lambda: c.get("k", timeout=60.0), settle=0.8
            )
            _assert_landed(dt)
            assert c._sock is None, "half-read frame survived the interrupt"
        finally:
            stop.set()
            c.close()
            lst.close()

    def test_mid_send_lands(self):
        """Fill the kernel buffers with a value larger than they can hold
        against a server that never reads: the client parks inside the
        sliced _send_all, where the raise must land too."""
        lst = socket.socket()
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        lst.bind(("127.0.0.1", 0))
        lst.listen(1)
        port = lst.getsockname()[1]
        c = StoreClient("127.0.0.1", port, timeout=60.0, retries=0)
        big = b"x" * (64 << 20)
        try:
            dt = _interrupt_parked(lambda: c.set("big", big), settle=0.8)
            _assert_landed(dt)
            # `sent` never flipped, the op was never applied, and the
            # partially-written socket is gone
            assert c._sock is None
        finally:
            c.close()
            lst.close()

    def test_mid_connect_lands(self):
        """The constructor's connect loop retries at quantum granularity
        (black-holed endpoint: a listener whose accept queue is full drops
        SYNs), so even a client that never got a socket is interruptible."""
        lst = socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(0)
        port = lst.getsockname()[1]
        fillers = []
        for _ in range(4):  # saturate the accept queue; never accepted
            s = socket.socket()
            s.setblocking(False)
            try:
                s.connect(("127.0.0.1", port))
            except BlockingIOError:
                pass
            fillers.append(s)
        time.sleep(0.2)
        try:
            dt = _interrupt_parked(
                lambda: StoreClient("127.0.0.1", port, connect_timeout=60.0)
            )
            _assert_landed(dt)
        finally:
            for s in fillers:
                s.close()
            lst.close()

    def test_mid_cross_shard_fanout_lands(self, tmp_path):
        """Cross-shard wait: per-shard worker threads park server-side
        while the caller sits in the sliced join — the raise targets the
        CALLER and must land between join slices."""
        group = ShardServerGroup(
            2, journal_base=str(tmp_path / "j")
        ).start()
        c = ShardedStoreClient(group.endpoints, timeout=60.0)
        try:
            keys = [f"fan/{i}" for i in range(8)]  # spreads over both shards
            dt = _interrupt_parked(lambda: c.wait(keys, timeout=60.0))
            _assert_landed(dt)
            # clean re-entry across the same clients
            c.multi_set({"fan/a": b"1", "fan/b": b"2"})
            assert c.multi_get(["fan/a", "fan/b"]) == [b"1", b"2"]
        finally:
            c.close()
            group.stop()

    def test_mid_mux_long_poll_lands_and_conn_survives(self, server):
        """Mux client: the caller parks in an Event.wait sliced at the
        quantum while the WAIT subscription is server-held.  The raise
        abandons the pending; the SHARED connection stays healthy for other
        callers."""
        c = MuxStoreClient("127.0.0.1", server.port, timeout=60.0)
        try:
            dt = _interrupt_parked(lambda: c.get("never/mux", timeout=60.0))
            _assert_landed(dt)
            # the multiplexed socket did NOT die with the abandoned caller
            c.set("mux/after", b"ok")
            assert c.get("mux/after", timeout=5.0) == b"ok"
        finally:
            c.close()


# -- brownout: live listener, wedged event loop -------------------------------


class TestBrownout:
    def test_single_client_escapes_via_first_byte_deadline(self, monkeypatch):
        monkeypatch.setenv("TPURX_STORE_TEST_BROWNOUT", "1")
        srv = StoreServer(host="127.0.0.1", port=0).start_in_thread()
        try:
            c = StoreClient("127.0.0.1", srv.port, timeout=60.0, retries=0)
            t0 = time.monotonic()
            with pytest.raises(StoreBrownout):
                c.set("k", b"v")
            dt = time.monotonic() - t0
            grace = _brownout_grace()
            assert dt < grace + 2.0, (
                f"brownout escape took {dt:.1f}s; first-byte deadline is "
                f"{grace:.1f}s — the op waited out io_timeout instead"
            )
            c.close()
        finally:
            srv.stop()

    def test_failover_client_retries_on_sibling(self, monkeypatch):
        """A browned-out endpoint still ACCEPTS connections, so failover
        cannot rely on connect errors: the brownout hook must rotate to the
        sibling before the retry."""
        monkeypatch.setenv("TPURX_STORE_TEST_BROWNOUT", "1")
        bad = StoreServer(host="127.0.0.1", port=0).start_in_thread()
        monkeypatch.delenv("TPURX_STORE_TEST_BROWNOUT")
        monkeypatch.setattr(
            "tpu_resiliency.store.client._brownout_grace", lambda: 0.5
        )
        good = StoreServer(host="127.0.0.1", port=0).start_in_thread()
        try:
            seed = StoreClient("127.0.0.1", good.port, timeout=10.0)
            seed.set("sib/k", b"v")
            seed.close()
            c = FailoverStoreClient(
                [f"127.0.0.1:{bad.port}", f"127.0.0.1:{good.port}"],
                timeout=60.0, retries=2,
            )
            t0 = time.monotonic()
            assert c.get("sib/k", timeout=30.0) == b"v"
            dt = time.monotonic() - t0
            # one brownout grace on the bad endpoint, then the sibling
            assert dt < _brownout_grace() + 10.0
            c.close()
        finally:
            bad.stop()
            good.stop()

    def test_sharded_brownout_trips_failover_to_promoted_spare(
        self, tmp_path, monkeypatch
    ):
        """The acceptance gate: brown out one shard subprocess, park a
        wait() on it, promote a spare — the parked caller escapes via
        StoreBrownout, rides store_shard_failover, adopts the bumped map
        and completes against the spare.  Nobody hangs."""
        from tpu_resiliency.store import promote_spare
        from tpu_resiliency.store.sharding import RetryPolicy, SHARD_MAP_KEY

        # Production timings (2s park slices, 2s brownout grace, 0.5-5s
        # failover backoff) make each victim touch cost ~4s — correct in the
        # field, needlessly slow here.  Tighten all three: the CONTRACT under
        # test (escape -> failover -> adoption) is timing-shape independent.
        monkeypatch.setattr(
            "tpu_resiliency.store.client._brownout_grace", lambda: 0.5
        )
        monkeypatch.setattr(StoreClient, "BLOCKING_SLICE_S", 0.5)
        fast_failover = RetryPolicy(
            max_attempts=None, base_delay=0.1, max_delay=0.5, deadline=60.0
        )

        ports = [free_port(), free_port()]
        spare_port = free_port()
        spare_ep = f"127.0.0.1:{spare_port}"
        endpoints = [f"127.0.0.1:{p}" for p in ports]
        procs = []
        spare_proc = None
        try:
            procs.append(spawn_shard_subprocess(ports[0]))
            procs.append(
                spawn_shard_subprocess(
                    ports[1], env={"TPURX_STORE_TEST_BROWNOUT": "1"}
                )
            )
            # the browned shard reads but never answers, so the map must be
            # seeded on the healthy one — which is also where recovery
            # discovery (_fetch_map_raw, excluding the victim) will look
            m = ShardMap(endpoints, spares=[spare_ep])
            seed = StoreClient("127.0.0.1", ports[0], timeout=10.0)
            seed.set(SHARD_MAP_KEY, m.to_json())
            c = ShardedStoreClient.from_bootstrap(
                "127.0.0.1", ports[0], timeout=60.0,
                failover_policy=fast_failover,
            )
            victim = 1

            # pick a key that routes to the browned-out shard
            key = next(
                f"bo/key/{i}" for i in range(256)
                if c.map.shard_for(f"bo/key/{i}".encode()) == victim
            )
            waited = {}

            def block():
                try:
                    c.wait([key], timeout=120.0)
                    waited["ok"] = True
                except Exception as exc:  # noqa: BLE001
                    waited["err"] = exc

            t = threading.Thread(target=block, daemon=True)
            t.start()
            time.sleep(0.5)  # parked against the brownout

            # the watchdog's moves: spare up, epoch-bumped map published on
            # the HEALTHY shard
            spare_proc = spawn_shard_subprocess(spare_port)
            mc = StoreClient("127.0.0.1", ports[0], timeout=10.0)
            promoted = promote_spare(mc, victim, spare_ep)
            mc.close()
            assert promoted.epoch == 1

            # release the waiter THROUGH the sharded client: its failover
            # episode must adopt the promoted endpoint first
            deadline = time.monotonic() + 90.0
            while time.monotonic() < deadline:
                try:
                    c.set(key, b"released")
                    break
                except StoreError:
                    time.sleep(0.5)
            t.join(timeout=90.0)
            assert not t.is_alive(), "waiter still parked on browned shard"
            assert waited.get("ok"), waited
            assert c.map.epoch == 1
            assert c.endpoints[victim] == ("127.0.0.1", spare_port)
            c.close()
        finally:
            for p in procs:
                p.kill()
            if spare_proc is not None:
                spare_proc.kill()


# -- non-idempotent resend rules survive the rewrite --------------------------


class TestResendRules:
    def test_non_idempotent_not_resent_after_full_send(self):
        """A connection that dies AFTER the whole ADD frame left must not be
        retried — the server may have applied it.  (The rewrite moved the
        send into sliced _send_all; the `sent` flip must still happen only
        after the last byte.)"""
        lst = socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(1)
        port = lst.getsockname()[1]

        def accept_then_reset():
            conn, _ = lst.accept()
            conn.recv(4096)  # whole (tiny) ADD frame arrives
            conn.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                struct.pack("ii", 1, 0),  # RST on close
            )
            conn.close()

        st = threading.Thread(target=accept_then_reset, daemon=True)
        st.start()
        c = StoreClient("127.0.0.1", port, timeout=10.0, retries=3)
        with pytest.raises(StoreError, match="not retrying non-idempotent"):
            c.add("ctr", 1)
        c.close()
        lst.close()
