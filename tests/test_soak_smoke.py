"""CI smoke of the chaos-soak regression gate (benchmarks/soak_launcher.py).

A compressed run of the full-stack gate: launcher + external journaled
control plane (randomly killed mid-run) + in-process ring + quorum
tripwire, randomized fault injection, detect->recover latencies derived
from the shared profiling JSONL with bounds asserted.  The 15-minute gate
is ``python benchmarks/soak_launcher.py --gate``; this smoke keeps the
same machinery honest on every suite run.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_soak_smoke_chaos_store_and_quorum():
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "benchmarks" / "soak_launcher.py"),
            "--seconds", "50", "--chaos-store", "--quorum",
            "--store-kill-every", "18", "28",
            "--exc-p", "0.02", "--qstall-p", "0.012", "--cwedge-p", "0.008",
            # generous bounds: this is a loaded 1-core CI host; the gate run
            # uses the defaults
            "--inner-bound-ms", "15000", "--outer-bound-ms", "60000",
        ],
        cwd=str(REPO), capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    last = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert last, proc.stdout[-2000:] + proc.stderr[-2000:]
    report = json.loads(last[-1])
    assert report["ok"], report
    assert report["store_kills"] >= 1, report
    assert report["monotone_progress"], report
    # both rings actually exercised
    assert report["inner_ring_recoveries"] >= 1, report
    # the abort ladder ran on inner trips with recorded stage outcomes
    assert report["ladder_ok"], report
    if report["inner_ring_recoveries"]:
        assert report["abort_stage_outcomes"].get(
            "fingerprint/released", 0
        ) >= 1, report
    total_outer_faults = (
        report["injected"]["crashes"] + report["injected"]["hangs"]
    )
    if total_outer_faults:
        assert report["cycles"] >= 1, report


def test_soak_smoke_corrupt_blob_fallback_restore():
    """The checkpoint-integrity campaign: every copy of the newest local
    checkpoint is bit-flipped mid-run and the gang hard-restarts; the
    restarted ranks must detect + quarantine the corruption and
    fallback-restore the next-oldest valid iteration on all ranks."""
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "benchmarks" / "soak_launcher.py"),
            "--seconds", "45", "--corrupt-blob", "bitflip",
        ],
        cwd=str(REPO), capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    last = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert last, proc.stdout[-2000:] + proc.stderr[-2000:]
    report = json.loads(last[-1])
    assert report["ok"], report
    assert report["ckpt_ok"], report
    assert report["corrupted_iter"] is not None, report
    assert report["cycles"] >= 1, report
    # every rank fallback-restored an OLDER iteration with nonzero depth,
    # detected corruption, and left quarantine debris
    fb = report["fallback_restores"]
    assert {r[0] for r in fb} == {0, 1}, report
    for _rank, it, depth, corrupt, quarantined, debris in fb:
        assert it < report["corrupted_iter"]
        assert depth >= 1 and corrupt >= 1 and quarantined >= 1 and debris >= 1


def test_soak_smoke_peer_mem_kill_falls_to_disk():
    """The peer-memory-stall fault class: at the drill step the serving
    rank drops every peer-memory chunk request, so each other rank —
    resident copy shed — must time the rung out and restore from its OWN
    disk blob at fallback depth 0 (colder source, same iteration)."""
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "benchmarks" / "soak_launcher.py"),
            "--seconds", "35", "--peer-mem-kill",
        ],
        cwd=str(REPO), capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    last = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert last, proc.stdout[-2000:] + proc.stderr[-2000:]
    report = json.loads(last[-1])
    assert report["ok"], report
    assert report["peer_ok"], report
    drills = report["peer_drills"]
    assert {d[0] for d in drills} == {0, 1}, report
    for rank, _it, disk_b, peer_b, depth in drills:
        if rank != 0:  # rank 0 serves (and restores warm from its resident)
            assert disk_b > 0 and peer_b == 0 and depth == 0, report


def test_soak_smoke_link_degrade_no_restart():
    """The link_degrade fault class: rank 0's primary collective lane is
    armed to stall past its deadline every call; the resilient wrapper
    must absorb the bad link IN PROCESS (deadline trip -> retry ->
    re-layout), every rank must finish, and the launcher ring must record
    ZERO restart cycles."""
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "benchmarks" / "soak_launcher.py"),
            "--seconds", "110", "--link-degrade",
        ],
        cwd=str(REPO), capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    last = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert last, proc.stdout[-2000:] + proc.stderr[-2000:]
    report = json.loads(last[-1])
    assert report["ok"], report
    assert report["coll_ok"], report
    # zero pod-wide restarts: the whole point of the degrade ladder
    assert report["cycles"] == 0, report
    # the armed rank walked the ladder: deadline trips AND degrades
    assert report["coll_degrades"] >= 1, report
    assert report["coll_timeouts"] >= 1, report
    # the healthy rank never degraded
    marks = {m[0]: m for m in report["coll_marks"]}
    assert marks[1][1] == 0, report


def test_soak_smoke_store_outage_mid_save():
    """The store-outage-mid-save fault class: targeted store kills inside
    rank 0's store-backed save windows; the unified retry policy must ride
    the save through the outage (saves_done tracks saves_started)."""
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "benchmarks" / "soak_launcher.py"),
            "--seconds", "55", "--store-kill-mid-save",
            "--save-every", "30", "--store-down", "2.0",
            # isolate the fault class: no random worker faults
            "--exc-p", "0", "--crash-p", "0", "--hang-p", "0",
            "--qstall-p", "0", "--cwedge-p", "0",
            "--inner-bound-ms", "15000", "--outer-bound-ms", "60000",
        ],
        cwd=str(REPO), capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    last = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert last, proc.stdout[-2000:] + proc.stderr[-2000:]
    report = json.loads(last[-1])
    assert report["ok"], report
    assert report["saves_started"] >= 1, report
    assert report["saves_ok"], report
    assert report["store_kills"] >= 1, report
    assert report["monotone_progress"], report


def test_soak_smoke_ramp_degrade_evacuates_before_hard_fault():
    """The predict-and-evacuate campaign: one rank's health/straggler
    scores ramp worse round by round; the fused per-rank risk must
    evacuate it BEFORE its hard-fault deadline (zero HARD FAULT markers),
    never evacuate the healthy rank, and the evacuated slot must
    warm-join from peer memory with zero disk bytes — no global
    restore."""
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "benchmarks" / "soak_launcher.py"),
            "--seconds", "120", "--ramp-degrade",
        ],
        cwd=str(REPO), capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    last = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert last, proc.stdout[-2000:] + proc.stderr[-2000:]
    report = json.loads(last[-1])
    assert report["ok"], report
    assert report["evac_ok"], report
    assert report["hard_faults"] == 0, report
    # only the ramping victim was evacuated, exactly once
    assert [r for r, _s in report["evacuations"]] == [1], report
    # the slot's replacement joined warm: peer bytes, zero disk bytes
    for warm, _it, peer_b, disk_b in report["evac_joins"]:
        assert warm == "True" and peer_b > 0 and disk_b == 0, report


def test_soak_smoke_store_longpoll_abort_lands():
    """The interruptible-long-poll campaign: every restart episode parks
    one rank deep in a server-held store wait() and injects a sibling
    fault; the async abort must LAND on the parked rank within the
    propagation budget + 2x poll quantum (the historical flake parked the
    raise behind one ~30s uninterruptible recv) and no rank may ever exit
    ret=None."""
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "benchmarks" / "soak_launcher.py"),
            "--seconds", "12", "--store-longpoll-abort",
            # loaded 1-core CI host: abort propagation (not the store
            # slicing) eats scheduler latency; the quantum contract itself
            # is asserted tightly by tests/test_store_interrupt.py
            "--longpoll-bound-s", "10.0",
        ],
        cwd=str(REPO), capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    last = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert last, proc.stdout[-2000:] + proc.stderr[-2000:]
    report = json.loads(last[-1])
    assert report["ok"], report
    assert report["lp_ok"], report
    assert report["lp_episodes_injected"] >= 1, report
    # every completed episode's abort landed on the parked rank
    assert report["lp_episodes_landed"] >= 1, report
    assert report["lp_ret_none"] == 0, report
    assert report["lp_land_ms_median"] is not None, report


def test_fault_schedule_generation_is_deterministic():
    """Same seed -> byte-identical injection timeline (the property the
    adaptive-vs-fixed A/B rests on); different seed -> different draws;
    the regime shift multiplies fault density after shift_at."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "soak_launcher", str(REPO / "benchmarks" / "soak_launcher.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    kw = dict(shift_at=1000, shift_mult=6.0)
    a = mod._gen_fault_schedule(7, 2, 4000, {"exception": 0.004}, **kw)
    b = mod._gen_fault_schedule(7, 2, 4000, {"exception": 0.004}, **kw)
    c = mod._gen_fault_schedule(8, 2, 4000, {"exception": 0.004}, **kw)
    assert a == b
    assert a["faults"] != c["faults"]
    pre = sum(1 for r in a["faults"].values() for s in r if int(s) < 1000)
    post = sum(1 for r in a["faults"].values() for s in r if int(s) >= 1000)
    # 3000 post-shift steps at 6x density vs 1000 pre-shift at 1x
    assert post > pre, (pre, post)


def test_soak_smoke_fault_shift_goodput_ab():
    """The adaptive-vs-fixed goodput A/B: both arms replay ONE seeded
    fault schedule; the adaptive arm closes the loop (estimator -> Young/
    Daly cadence -> SaveScheduler) on real telemetry.  The 1.1x gain gate
    is waived on 1-core hosts; the mechanics must still hold: both arms
    finish ok and a finite gain is measured."""
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "benchmarks" / "soak_launcher.py"),
            "--fault-shift", "--seconds", "20", "--fault-seed", "11",
        ],
        cwd=str(REPO), capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    last = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert last, proc.stdout[-2000:] + proc.stderr[-2000:]
    report = json.loads(last[-1])
    assert report["ok"], report
    assert report["arms_ok"], report
    assert report["policy_goodput_gain"] > 0, report
    assert report["fixed_progress"] > 0, report
    assert report["adaptive_progress"] > 0, report
