"""Deep health-check suite: TPU sysfs, kernel log, windowed counters,
node-health daemon, distributed storage, and the monitor-hosted health loop.

Reference analog: ``tests/shared_utils`` health-check unit coverage plus the
watchdog-hosted GPU/NIC loops (``rank_monitor_server.py:122``).
"""

import json
import os
import socket
import threading
import time

import pytest

from tpu_resiliency.health import (
    CounterDeltaWindowCheck,
    DistributedStorageHealthCheck,
    KernelLogHealthCheck,
    NodeHealthDaemonCheck,
    TpuSysHealthCheck,
    WindowedErrorCounter,
    build_passive_checks,
)
from tpu_resiliency.health.device import DeviceHealthCheck


# -- tpu sysfs ---------------------------------------------------------------


def _fake_accel_tree(tmp_path, n):
    sys_accel = tmp_path / "sys_accel"
    sys_accel.mkdir(exist_ok=True)
    for i in range(n):
        (sys_accel / f"accel{i}").mkdir(exist_ok=True)
    return str(sys_accel)


def test_tpu_sys_counts_chips(tmp_path):
    root = _fake_accel_tree(tmp_path, 4)
    check = TpuSysHealthCheck(sys_accel=root, dev_glob=str(tmp_path / "none*"))
    r = check.run()
    assert r.healthy and "4 accel" in r.message


def test_tpu_sys_expected_chips(tmp_path):
    root = _fake_accel_tree(tmp_path, 2)
    check = TpuSysHealthCheck(
        sys_accel=root, dev_glob=str(tmp_path / "none*"), expected_chips=4
    )
    r = check.run()
    assert not r.healthy and "expected 4" in r.message


def test_tpu_sys_learns_count_and_detects_drop(tmp_path):
    root = _fake_accel_tree(tmp_path, 4)
    check = TpuSysHealthCheck(sys_accel=root, dev_glob=str(tmp_path / "none*"))
    assert check.run().healthy
    # a chip falls off the bus
    os.rmdir(os.path.join(root, "accel3"))
    r = check.run()
    assert not r.healthy and "expected 4" in r.message


def test_tpu_sys_absent_driver_skips_unless_required(tmp_path):
    check = TpuSysHealthCheck(
        sys_accel=str(tmp_path / "missing"), dev_glob=str(tmp_path / "none*")
    )
    assert check.run().healthy  # dev box: skip, don't fail
    required = TpuSysHealthCheck(
        sys_accel=str(tmp_path / "missing"),
        dev_glob=str(tmp_path / "none*"),
        required=True,
    )
    assert not required.run().healthy


# -- kernel log --------------------------------------------------------------


def test_kernel_log_baselines_then_detects(tmp_path):
    path = tmp_path / "kern.log"
    path.write_text("old: tpu error before monitor started\n")
    check = KernelLogHealthCheck(source=str(path), window_s=60.0)
    assert check.run().healthy  # history is baseline, not failure
    with open(path, "a") as f:
        f.write("normal line\naccel accel0: fatal error, chip reset\n")
    r = check.run()
    assert not r.healthy and "chip reset" in r.message


def test_kernel_log_threshold_and_window(tmp_path):
    path = tmp_path / "kern.log"
    path.write_text("")
    check = KernelLogHealthCheck(source=str(path), window_s=0.3, threshold=2)
    assert check.run().healthy
    with open(path, "a") as f:
        f.write("EDAC MC0: 1 UE on chip\n")
    assert check.run().healthy  # 1 hard < threshold 2
    with open(path, "a") as f:
        f.write("Machine Check event\n")
    assert not check.run().healthy  # 2 hard within window
    time.sleep(0.35)
    assert check.run().healthy  # window expired


def test_kernel_log_soft_faults_need_repeats(tmp_path):
    """A single transient event (AER spam, link flap, one NFS hiccup) must
    NOT exclude the node — exclusion is sticky; soft faults trip only on
    repetition within the window (ADVICE r2: threshold=1 + broad patterns
    made any benign event a permanent exclusion)."""
    path = tmp_path / "kern.log"
    path.write_text("")
    check = KernelLogHealthCheck(source=str(path), window_s=60.0)
    assert check.run().healthy
    with open(path, "a") as f:
        f.write("pcieport 0000:00:01.0: AER: error received\n")
    assert check.run().healthy  # one transient: fine
    with open(path, "a") as f:
        f.write("eth0: Link is Down\n")
    assert check.run().healthy  # two transients: still fine
    with open(path, "a") as f:
        f.write("nfs: server storage1 not responding, I/O error\n")
    r = check.run()
    assert not r.healthy and "transient" in r.message  # third trips


def test_kernel_log_oom_scoped_to_workers(tmp_path):
    """A host cgroup OOM of an unrelated process must never count; a worker
    OOM counts as a (soft) fault."""
    path = tmp_path / "kern.log"
    path.write_text("")
    check = KernelLogHealthCheck(source=str(path), window_s=60.0, soft_threshold=1)
    assert check.run().healthy
    with open(path, "a") as f:
        f.write("Out of memory: Killed process 1234 (chrome) total-vm:1kB\n")
    assert check.run().healthy  # unrelated process: ignored
    with open(path, "a") as f:
        f.write("Out of memory: Killed process 999 (python3) total-vm:1kB\n")
    assert not check.run().healthy


def test_kernel_log_rotation(tmp_path):
    path = tmp_path / "kern.log"
    path.write_text("x" * 100)
    check = KernelLogHealthCheck(source=str(path), window_s=60.0)
    assert check.run().healthy
    path.write_text("Machine Check event\n")  # rotated: smaller than offset
    assert not check.run().healthy


# -- windowed counters -------------------------------------------------------


def test_windowed_counter_math():
    w = WindowedErrorCounter(window_s=10.0)
    w.record(3, now=100.0)
    w.record(2, now=105.0)
    assert w.count(now=106.0) == 5
    assert w.count(now=111.0) == 2  # first event aged out
    assert w.count(now=200.0) == 0


def test_counter_delta_window(tmp_path):
    f1 = tmp_path / "rx_errors"
    f1.write_text("1000")
    check = CounterDeltaWindowCheck(
        counter_globs=[str(tmp_path / "*_errors")], window_s=0.3, threshold=2
    )
    assert check.run().healthy  # baseline
    f1.write_text("1001")
    assert check.run().healthy  # 1 < threshold
    f1.write_text("1003")
    r = check.run()
    assert not r.healthy and "3 counter error" in r.message
    time.sleep(0.35)
    assert check.run().healthy  # window expired
    f1.write_text("5")  # counter reset (driver reload) -> re-baseline
    assert check.run().healthy


# -- node-health daemon ------------------------------------------------------


class _FakeDaemon:
    def __init__(self, reply):
        self.reply = reply
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self._t = threading.Thread(target=self._serve, daemon=True)
        self._t.start()

    def _serve(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                conn.recv(4096)
                conn.sendall(json.dumps(self.reply).encode() + b"\n")

    def close(self):
        self.sock.close()


def test_daemon_healthy_and_unhealthy():
    d = _FakeDaemon({"healthy": True})
    try:
        assert NodeHealthDaemonCheck(f"127.0.0.1:{d.port}").run().healthy
        d.reply = {"healthy": False, "reason": "ICI link flap storm"}
        r = NodeHealthDaemonCheck(f"127.0.0.1:{d.port}").run()
        assert not r.healthy and "ICI link flap" in r.message
    finally:
        d.close()


def test_daemon_malformed_endpoint_honors_required():
    # 'unix:/x' (single slash) and 'myhost' (no port) are config mistakes,
    # not node failures: they must not exclude nodes when the daemon is
    # optional
    r = NodeHealthDaemonCheck("unix:/run/health.sock").run()
    assert r.healthy and "bad health daemon endpoint" in r.message
    assert NodeHealthDaemonCheck("myhost").run().healthy
    assert not NodeHealthDaemonCheck("myhost", required=True).run().healthy


def test_daemon_optional_vs_required(monkeypatch):
    monkeypatch.delenv("TPURX_NODE_HEALTH_ENDPOINT", raising=False)
    assert NodeHealthDaemonCheck().run().healthy  # unconfigured -> skip
    assert not NodeHealthDaemonCheck(required=True).run().healthy
    # unreachable endpoint: degraded observability unless required
    assert NodeHealthDaemonCheck("127.0.0.1:1", timeout=0.5).run().healthy
    assert not NodeHealthDaemonCheck(
        "127.0.0.1:1", timeout=0.5, required=True
    ).run().healthy


# -- distributed storage -----------------------------------------------------


def test_distributed_storage_gathers(store, store_server, tmp_path):
    from tpu_resiliency.store import StoreClient

    path = str(tmp_path / "shared_ckpt")
    other = StoreClient("127.0.0.1", store_server.port)

    def rank1():
        DistributedStorageHealthCheck(
            other, rank=1, world=2, path=path, gather_timeout=10.0
        ).run()

    t = threading.Thread(target=rank1)
    t.start()
    r = DistributedStorageHealthCheck(
        store, rank=0, world=2, path=path, gather_timeout=10.0
    ).run()
    t.join()
    other.close()
    assert r.healthy and "all 2 rank" in r.message


def test_distributed_storage_reports_missing_rank(store, tmp_path):
    r = DistributedStorageHealthCheck(
        store, rank=0, world=2, path=str(tmp_path / "p"), gather_timeout=0.5
    ).run()
    assert not r.healthy and "no storage report from ranks [1]" in r.message


# -- device probe stats ------------------------------------------------------


def test_device_probe_judges_hbm_leak():
    check = DeviceHealthCheck(max_idle_hbm_frac=0.5)
    stats = [{"id": 0, "kind": "TPU v5", "platform": "tpu",
              "bytes_in_use": 9 << 30, "bytes_limit": 16 << 30}]
    r = check._judge_stats("TPURX_DEVICE_OK " + json.dumps(stats))
    assert not r.healthy and "leaked grants" in r.message
    stats[0]["bytes_in_use"] = 1 << 20
    r = check._judge_stats("TPURX_DEVICE_OK " + json.dumps(stats))
    assert r.healthy and "TPU v5" in r.message


# -- factory -----------------------------------------------------------------


def test_build_passive_checks_spec(tmp_path):
    chain = build_passive_checks(
        "node_resources,kernel_log",
        kernel_log_source=str(tmp_path / "k.log"),
    )
    assert len(chain.checks) == 2
    with pytest.raises(ValueError):
        build_passive_checks("device")  # intrusive probe is not passive
    # storage_path only materializes when a path is configured
    assert len(build_passive_checks("storage_path").checks) == 0
    assert len(
        build_passive_checks("storage_path", storage_path=str(tmp_path)).checks
    ) == 1


# -- monitor-hosted health loop ---------------------------------------------


def test_monitor_survives_bad_health_spec(tmp_path):
    """A typo'd check spec must not take the watchdog (hang detection!) down."""
    from tpu_resiliency.fault_tolerance import FaultToleranceConfig
    from tpu_resiliency.fault_tolerance.rank_monitor_server import RankMonitorServer

    cfg = FaultToleranceConfig(
        workload_check_interval=0.1,
        monitor_health_check_interval=0.1,
        monitor_health_checks="kernel-log",  # typo: dash, not underscore
    )
    sock_path = str(tmp_path / "monitor.sock")
    proc, ctrl = RankMonitorServer.run_in_subprocess(cfg, sock_path)
    try:
        time.sleep(0.5)
        assert proc.is_alive()  # watchdog survived the bad spec
    finally:
        ctrl.send({"cmd": "shutdown"})
        proc.join(timeout=5)
        if proc.is_alive():
            proc.terminate()


def test_monitor_emits_health_failure_event(tmp_path):
    from tpu_resiliency.fault_tolerance import FaultToleranceConfig
    from tpu_resiliency.fault_tolerance.rank_monitor_server import RankMonitorServer

    klog = tmp_path / "kern.log"
    klog.write_text("")
    cfg = FaultToleranceConfig(
        workload_check_interval=0.1,
        monitor_health_check_interval=0.1,
        monitor_health_checks="kernel_log",
        monitor_health_kernel_log=str(klog),
    )
    sock_path = str(tmp_path / "monitor.sock")
    proc, ctrl = RankMonitorServer.run_in_subprocess(cfg, sock_path)
    try:
        time.sleep(0.4)  # a few healthy iterations first
        assert not ctrl.poll(0)
        with open(klog, "a") as f:
            f.write("accel accel0: hardware fault, link down\n")
        deadline = time.monotonic() + 10
        evt = None
        while time.monotonic() < deadline:
            if ctrl.poll(0.1):
                evt = ctrl.recv()
                break
        assert evt is not None, "no health event from monitor"
        assert evt["event"] == "health_failure"
        assert "hardware fault" in evt["message"]
    finally:
        ctrl.send({"cmd": "shutdown"})
        proc.join(timeout=5)
        if proc.is_alive():
            proc.terminate()
