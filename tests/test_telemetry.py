"""Telemetry plane unit tests: registry semantics, OpenMetrics rendering,
cross-rank aggregation, trace export, and the disabled-path cost contract."""

import json
import threading
import time
import urllib.request

import pytest

from tpu_resiliency.telemetry import (
    DEFAULT_NS_BUCKETS,
    NOOP,
    Registry,
)
from tpu_resiliency.telemetry.aggregate import (
    CrossRankAggregator,
    aggregate_snapshots,
    outliers,
    render_job_metrics,
)
from tpu_resiliency.telemetry.exporter import (
    MetricsHTTPServer,
    TextfileSink,
    render_openmetrics,
)
from tpu_resiliency.telemetry.trace import to_chrome_trace


# ---- registry ---------------------------------------------------------------


class TestRegistry:
    def test_counter_and_gauge(self):
        r = Registry(enabled=True)
        c = r.counter("tpurx_x_total", "help")
        c.inc()
        c.inc(4)
        assert c.value == 5
        g = r.gauge("tpurx_g")
        g.set(2.5)
        g.inc()
        g.dec(0.5)
        assert g.value == 3.0

    def test_counter_requires_total_suffix_and_valid_name(self):
        r = Registry(enabled=True)
        with pytest.raises(ValueError):
            r.counter("tpurx_x")
        with pytest.raises(ValueError):
            r.gauge("bad name!")

    def test_counters_never_decrease(self):
        r = Registry(enabled=True)
        with pytest.raises(ValueError):
            r.counter("tpurx_x_total").inc(-1)

    def test_labels(self):
        r = Registry(enabled=True)
        c = r.counter("tpurx_ops_total", labels=("op",))
        c.labels("GET").inc(2)
        c.labels(op="SET").inc()
        assert r.value_of("tpurx_ops_total", {"op": "GET"}) == 2
        assert r.value_of("tpurx_ops_total", {"op": "SET"}) == 1
        with pytest.raises(ValueError):
            c.labels("a", "b")

    def test_duplicate_registration(self):
        r = Registry(enabled=True)
        a = r.counter("tpurx_x_total")
        assert r.counter("tpurx_x_total") is a  # idempotent
        with pytest.raises(ValueError):
            r.gauge("tpurx_x_total")  # kind conflict
        with pytest.raises(ValueError):
            r.counter("tpurx_x_total", labels=("op",))  # label conflict

    def test_histogram_buckets_and_quantile(self):
        r = Registry(enabled=True)
        h = r.histogram("tpurx_lat_ns", buckets=(10, 100, 1000))
        for v in (5, 50, 50, 500, 5000):
            h.observe(v)
        assert h.count == 5
        d = h._value_dict()
        assert d["counts"] == [1, 2, 1, 1]
        assert d["sum"] == 5605
        assert h.quantile(0.5) == 100  # 3rd of 5 lands in the <=100 bucket

    def test_histogram_timer(self):
        r = Registry(enabled=True)
        h = r.histogram("tpurx_t_ns")
        with h.time_ns():
            pass
        assert h.count == 1

    def test_thread_safety(self):
        r = Registry(enabled=True)
        c = r.counter("tpurx_mt_total")

        def spin():
            for _ in range(10_000):
                c.inc()

        threads = [threading.Thread(target=spin) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 80_000


class TestDisabledPath:
    def test_disabled_returns_shared_noop(self):
        r = Registry(enabled=False)
        c = r.counter("tpurx_x_total")
        assert c is NOOP
        assert r.histogram("tpurx_h_ns") is NOOP
        assert r.gauge("tpurx_g").labels() is NOOP
        c.inc()
        NOOP.observe(5)
        with NOOP.time_ns():
            pass
        assert r.collect() == []  # nothing ever materializes
        # the catalog still knows the names (one-time registration cost)
        assert "tpurx_x_total" in r.names()

    def test_env_switch(self, monkeypatch):
        monkeypatch.setenv("TPURX_TELEMETRY", "0")
        assert Registry().counter("tpurx_e_total") is NOOP
        monkeypatch.setenv("TPURX_TELEMETRY", "1")
        assert Registry().counter("tpurx_e_total") is not NOOP

    def test_increment_cost_microbenchmark(self):
        """Acceptance contract: disabled increments are no-ops, enabled
        increments are sub-microsecond.  Best-of-5 batches to shrug off CI
        scheduler noise."""
        n = 50_000

        def per_op(c):
            best = float("inf")
            for _ in range(5):
                t0 = time.perf_counter_ns()
                for _ in range(n):
                    c.inc()
                best = min(best, (time.perf_counter_ns() - t0) / n)
            return best

        disabled = per_op(Registry(enabled=False).counter("tpurx_b_total"))
        enabled = per_op(Registry(enabled=True).counter("tpurx_b_total"))
        assert disabled < 1_000, f"disabled inc cost {disabled:.0f}ns"
        assert enabled < 1_000, f"enabled inc cost {enabled:.0f}ns"


# ---- exporter ---------------------------------------------------------------


OM_SAMPLE_RE = (
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? [^ ]+$'
)


def assert_valid_openmetrics(text: str):
    import re

    lines = text.strip().split("\n")
    assert lines[-1] == "# EOF"
    for line in lines[:-1]:
        if line.startswith("#"):
            assert line.startswith(("# TYPE ", "# HELP ")), line
        else:
            assert re.match(OM_SAMPLE_RE, line), f"bad sample line: {line!r}"


def _populated_registry():
    r = Registry(enabled=True)
    r.counter("tpurx_ops_total", "ops", labels=("op",)).labels("GET").inc(7)
    r.gauge("tpurx_depth", "queue depth").set(3)
    h = r.histogram("tpurx_lat_ns", "latency")
    h.observe(2_000)
    h.observe(3e9)
    return r


class TestExporter:
    def test_render_valid_and_complete(self):
        text = render_openmetrics(_populated_registry())
        assert_valid_openmetrics(text)
        assert 'tpurx_ops_total{op="GET"} 7' in text
        assert "# TYPE tpurx_ops counter" in text  # family drops _total
        assert "tpurx_depth 3" in text
        assert "tpurx_lat_ns_count 2" in text
        assert 'tpurx_lat_ns_bucket{le="+Inf"} 2' in text

    def test_label_escaping(self):
        r = Registry(enabled=True)
        r.counter("tpurx_esc_total", labels=("p",)).labels('a"b\\c\nd').inc()
        text = render_openmetrics(r)
        assert '{p="a\\"b\\\\c\\nd"}' in text

    def test_http_server_scrape(self):
        server = MetricsHTTPServer(_populated_registry(), host="127.0.0.1").start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=5
            ) as resp:
                assert resp.status == 200
                assert "openmetrics-text" in resp.headers["Content-Type"]
                body = resp.read().decode()
            assert_valid_openmetrics(body)
            assert 'tpurx_ops_total{op="GET"} 7' in body
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/healthz", timeout=5
            ) as resp:
                assert resp.read() == b"ok"
        finally:
            server.close()

    def test_serve_from_env_local_rank_port_offset(self, monkeypatch):
        from tpu_resiliency.telemetry import exporter as exp_mod

        srv = MetricsHTTPServer(Registry(enabled=True), host="127.0.0.1").start()
        base = srv.port  # a port we know is free... after close
        srv.close()
        monkeypatch.setenv("TPURX_METRICS_PORT", str(base))
        monkeypatch.setenv("TPURX_LOCAL_RANK", "0")
        started = exp_mod.serve_from_env(Registry(enabled=True))
        try:
            assert [s.port for s in started] == [base]
        finally:
            for s in started:
                s.close()

    def test_textfile_sink_expansion_and_atomicity(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPURX_RANK", "3")
        sink = TextfileSink(
            str(tmp_path / "metrics_%r.prom"), _populated_registry()
        )
        path = sink.write_once()
        assert path.endswith("metrics_3.prom")
        with open(path) as f:
            assert_valid_openmetrics(f.read())
        assert not list(tmp_path.glob("*.tmp"))


# ---- aggregation ------------------------------------------------------------


def _rank_registry(rank):
    r = Registry(enabled=True)
    r.counter("tpurx_drops_total").inc(rank * 10)
    r.gauge("tpurx_score").set(1.0 / (rank + 1))
    h = r.histogram("tpurx_lat_ns", buckets=(100, 1000))
    h.observe(50 * (rank + 1))
    return r


class TestAggregate:
    def test_sums_maxes_outliers(self):
        snaps = {rank: _rank_registry(rank).snapshot() for rank in range(4)}
        agg = aggregate_snapshots(snaps)
        drops = agg["tpurx_drops_total"]["samples"][json.dumps({})]
        assert drops["sum"] == 60
        assert drops["max"] == 30 and drops["max_rank"] == 3
        assert drops["min"] == 0
        assert outliers(agg, "tpurx_drops_total", k=2) == [(3, 30.0), (2, 20.0)]
        lat = agg["tpurx_lat_ns"]["samples"][json.dumps({})]
        assert lat["count"] == 4
        assert sum(lat["counts"]) == 4

    def test_render_job_metrics(self):
        snaps = {rank: _rank_registry(rank).snapshot() for rank in range(2)}
        text = render_job_metrics(aggregate_snapshots(snaps))
        assert 'tpurx_drops_total{agg="sum"} 10' in text
        assert 'tpurx_drops_total{agg="max",rank="1"} 10' in text
        assert 'tpurx_score{agg="max",rank="0"} 1' in text

    def test_cross_rank_gather_over_store(self, store):
        """Full collective: N rank threads publish, rank 0 reduces, round
        keys are cleaned up (the straggler reporting round pattern)."""
        world = 3
        regs = {r: _rank_registry(r) for r in range(world)}
        results = {}

        def run(rank):
            aggr = CrossRankAggregator(store.clone(), rank, world)
            results[rank] = aggr.round(regs[rank], timeout=20.0)

        threads = [threading.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert results[1] is None and results[2] is None
        agg = results[0]
        drops = agg["tpurx_drops_total"]["samples"][json.dumps({})]
        assert drops["sum"] == 30 and drops["max_rank"] == 2
        assert store.list_keys("telemetry/round/0/") == []


# ---- trace export -----------------------------------------------------------


def _evt(event, mono_ns, **extra):
    return {"ts": 0.0, "mono_ns": mono_ns, "event": event, "pid": 1, **extra}


class TestTrace:
    def test_pairs_spans_per_rank(self):
        events = [
            _evt("rendezvous_started", 1_000, rank=0, round=1),
            _evt("rendezvous_completed", 4_000, rank=0, round=1, participants=2),
            _evt("checkpoint_save_started", 2_000, rank=1),
            _evt("checkpoint_save_finalized", 9_000, rank=1),
            _evt("hang_detected", 5_000, rank=0, reason="x"),
        ]
        trace = to_chrome_trace(events)
        spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        assert {s["name"] for s in spans} == {"rendezvous", "checkpoint_save"}
        rdzv = next(s for s in spans if s["name"] == "rendezvous")
        assert rdzv["pid"] == 0 and rdzv["ts"] == 0.0 and rdzv["dur"] == 3.0
        assert rdzv["args"]["participants"] == 2
        save = next(s for s in spans if s["name"] == "checkpoint_save")
        assert save["pid"] == 1 and save["dur"] == 7.0
        instants = [e for e in trace["traceEvents"] if e["ph"] == "i"]
        assert any(e["name"] == "hang_detected" for e in instants)
        metas = [e for e in trace["traceEvents"] if e["ph"] == "M"]
        assert {m["args"]["name"] for m in metas} == {"rank 0", "rank 1"}

    def test_unfinished_span_becomes_instant(self):
        trace = to_chrome_trace([_evt("inprocess_restart_started", 1_000, rank=2)])
        names = [e["name"] for e in trace["traceEvents"]]
        assert "inprocess_restart (unfinished)" in names

    def test_health_checks_match_by_name(self):
        events = [
            _evt("health_check_started", 1_000, rank=0, check="tpu"),
            _evt("health_check_started", 2_000, rank=0, check="storage"),
            _evt("health_check_completed", 3_000, rank=0, check="tpu", healthy=True),
            _evt("health_check_completed", 8_000, rank=0, check="storage", healthy=True),
        ]
        spans = [
            e for e in to_chrome_trace(events)["traceEvents"] if e["ph"] == "X"
        ]
        by_check = {s["args"]["check"]: s for s in spans}
        assert by_check["tpu"]["dur"] == 2.0
        assert by_check["storage"]["dur"] == 6.0

    def test_cli_end_to_end(self, tmp_path):
        """`python -m tpu_resiliency.telemetry.trace` on a real
        ProfilingRecorder JSONL file emits spans pairing the recorder's
        start/end events (acceptance criterion)."""
        import subprocess
        import sys

        from tpu_resiliency.utils.profiling import ProfilingEvent, ProfilingRecorder

        jsonl = tmp_path / "prof.jsonl"
        rec = ProfilingRecorder(path=str(jsonl))
        rec.record(ProfilingEvent.RENDEZVOUS_STARTED, rank=0, round=0)
        rec.record(ProfilingEvent.RENDEZVOUS_COMPLETED, rank=0, round=0)
        rec.record(ProfilingEvent.CHECKPOINT_SAVE_STARTED, rank=0)
        rec.record(ProfilingEvent.CHECKPOINT_SAVE_FINALIZED, rank=0)
        rec.record(ProfilingEvent.HANG_DETECTED, rank=0, reason="test")
        rec.close()
        out = tmp_path / "trace.json"
        proc = subprocess.run(
            [
                sys.executable, "-m", "tpu_resiliency.telemetry.trace",
                str(jsonl), "-o", str(out),
            ],
            capture_output=True, text=True, timeout=60,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr
        trace = json.loads(out.read_text())
        spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        assert {s["name"] for s in spans} == {"rendezvous", "checkpoint_save"}
        assert all(s["dur"] >= 0 for s in spans)
