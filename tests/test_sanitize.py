"""Runtime lock-order sanitizer (utils/sanitize.py).

Every test runs the sanitizer in a SUBPROCESS: install() patches
``threading.Lock``/``threading.RLock`` process-globally, which must never
leak into the test runner.  The integration tests close the static<->runtime
loop: the same fixture module is linted (TPURX011, PLAUSIBLE) and executed
under the sanitizer, and the produced witness promotes the finding to
CONFIRMED — or prunes it when the runtime only ever saw one order.
"""

import json
import os
import subprocess
import sys
import textwrap

from tpu_resiliency.utils.env import disarm_platform_sitecustomize
from tpurx_lint import run_lint

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

# decl lines 6 and 7: the lock table keys witness edges by creation site
FIXTURE = """\
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._b:
            with self._a:
                pass
"""


def run_py(script, timeout=60):
    env = disarm_platform_sitecustomize(dict(os.environ))
    env.pop("TPURX_SANITIZE", None)
    proc = subprocess.run(
        [sys.executable, "-c", textwrap.dedent(script)],
        capture_output=True, text=True, timeout=timeout,
        cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return proc.stdout


class TestSanitizerBehavior:
    def test_inversion_trips_and_is_witnessed(self, tmp_path):
        wit = tmp_path / "w.jsonl"
        out = run_py(f"""
            import threading
            from tpu_resiliency.utils import sanitize
            sanitize.install(witness_path={str(wit)!r})
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
            try:
                with b:
                    with a:
                        pass
                print("NOTRIP")
            except sanitize.LockOrderViolation:
                print("TRIP")
            sanitize.close_witness()
        """)
        assert "TRIP" in out
        recs = [json.loads(l) for l in wit.read_text().splitlines()]
        events = [r["event"] for r in recs]
        assert "meta" in events and "edge" in events and "cycle" in events
        cyc = next(r for r in recs if r["event"] == "cycle")
        assert cyc["kind"] == "order" and len(cyc["chain"]) >= 2

    def test_rlock_reentrancy_and_condition_wait_clean(self, tmp_path):
        wit = tmp_path / "w.jsonl"
        run_py(f"""
            import threading, time
            from tpu_resiliency.utils import sanitize
            sanitize.install(witness_path={str(wit)!r})
            r = threading.RLock()
            with r:
                with r:
                    pass
            cv = threading.Condition()
            hit = []
            def waiter():
                with cv:
                    cv.wait(timeout=5)
                    hit.append(1)
            t = threading.Thread(target=waiter, daemon=True)
            t.start()
            time.sleep(0.2)
            with cv:
                cv.notify_all()
            t.join(timeout=5)
            assert hit, "condition wait/notify must work through the wrapper"
            ev = threading.Event(); ev.set(); assert ev.is_set()
            import queue
            q = queue.Queue(); q.put(1); assert q.get(timeout=1) == 1
            assert sanitize.stats()["cycles"] == 0
            sanitize.close_witness()
        """)
        recs = [json.loads(l) for l in wit.read_text().splitlines()]
        assert not [r for r in recs if r["event"] == "cycle"]

    def test_lock_self_reacquire_trips(self, tmp_path):
        out = run_py("""
            import threading
            from tpu_resiliency.utils import sanitize
            sanitize.install()
            mu = threading.Lock()
            try:
                with mu:
                    mu.acquire()
                print("NOTRIP")
            except sanitize.LockOrderViolation as e:
                assert "self-deadlock" in str(e)
                print("TRIP")
        """)
        assert "TRIP" in out

    def test_install_from_env_via_package_import(self, tmp_path):
        wit = tmp_path / "w.jsonl"
        env = disarm_platform_sitecustomize(dict(os.environ))
        env["TPURX_SANITIZE"] = "1"
        env["TPURX_SANITIZE_WITNESS_PATH"] = str(tmp_path / "w.%r.jsonl")
        proc = subprocess.run(
            [sys.executable, "-c",
             "import tpu_resiliency\n"
             "from tpu_resiliency.utils import sanitize\n"
             "assert sanitize.stats()['installed']\n"
             "import threading\n"
             "a = threading.Lock()\n"
             "with a: pass\n"
             "print('path', sanitize.stats()['witness_path'])\n"],
            capture_output=True, text=True, timeout=60, cwd=REPO, env=env,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        # %r expanded to the (default 0) rank
        assert str(tmp_path / "w.0.jsonl") in proc.stdout
        assert (tmp_path / "w.0.jsonl").exists()
        del wit


class TestWitnessFeedbackLoop:
    def _fixture(self, tmp_path):
        mod = tmp_path / "tpu_resiliency" / "m.py"
        mod.parent.mkdir(parents=True, exist_ok=True)
        mod.write_text(FIXTURE)
        return mod

    def _run_fixture(self, tmp_path, mod, wit, body):
        run_py(f"""
            from tpu_resiliency.utils import sanitize
            sanitize.install(witness_path={str(wit)!r})
            src = open({str(mod)!r}).read()
            ns = {{}}
            exec(compile(src, {str(mod)!r}, "exec"), ns)
            c = ns["C"]()
            {body}
            sanitize.close_witness()
        """)

    def test_sanitizer_witness_confirms_static_cycle(self, tmp_path):
        mod = self._fixture(tmp_path)
        static = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                          use_baseline=False, rule_ids=["TPURX011"])
        assert len(static.findings) == 1
        assert "[PLAUSIBLE]" in static.findings[0].message

        wit = tmp_path / "w.jsonl"
        self._run_fixture(tmp_path, mod, wit, """
            c.one()
            try:
                c.two()
            except sanitize.LockOrderViolation:
                pass  # expected: the sanitizer trips on the inversion
        """)
        confirmed = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                             use_baseline=False, rule_ids=["TPURX011"],
                             witness_path=str(wit))
        assert len(confirmed.findings) == 1
        assert "[CONFIRMED]" in confirmed.findings[0].message

    def test_sanitizer_witness_prunes_one_sided_order(self, tmp_path):
        mod = self._fixture(tmp_path)
        wit = tmp_path / "w.jsonl"
        self._run_fixture(tmp_path, mod, wit, "c.one()")
        pruned = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                          use_baseline=False, rule_ids=["TPURX011"],
                          witness_path=str(wit))
        assert not pruned.findings
        assert len(pruned.witness_pruned) == 1
        assert "[PRUNED]" in pruned.witness_pruned[0].message
