"""Unit tests: staged abort ladder, unified retry policy, dispatch-tail
fingerprint, and the fingerprint analyzer — the in-process pieces of the
measured degradation ladder (monitor-kill backstop stays the bottom rung;
its end-to-end coverage lives in tests/test_layered_restart.py)."""

import threading
import time

import pytest

from tpu_resiliency.attribution.trace_analyzer import analyze_fingerprints
from tpu_resiliency.inprocess import Compose
from tpu_resiliency.inprocess.abort import (
    ESCALATE,
    FAILED,
    RELEASED,
    SKIPPED,
    TIMED_OUT,
    AbortLadder,
    AbortStage,
    ClearJaxCaches,
    EscalateAbort,
    FingerprintStage,
    FnStage,
    ShrinkMeshStage,
)
from tpu_resiliency.inprocess.attribution import Interruption, InterruptionRecord
from tpu_resiliency.inprocess.fingerprint import (
    DispatchTail,
    parse_fingerprints,
    read_tail,
)
from tpu_resiliency.telemetry import get_registry
from tpu_resiliency.utils.retry import (
    Retrier,
    RetryExhausted,
    RetryPolicy,
    retry_call,
)


class _Stage(AbortStage):
    def __init__(self, name, fn=None, timeout=None):
        super().__init__(timeout)
        self.name = name
        self.fn = fn or (lambda: None)

    def release(self, state=None):
        return self.fn()


class TestAbortLadder:
    def test_rung_order_and_outcomes(self):
        order = []
        lad = AbortLadder(
            _Stage("a", lambda: order.append("a")),
            _Stage("b", lambda: (_ for _ in ()).throw(RuntimeError("boom"))),
            _Stage("c", lambda: order.append("c")),
        )
        lad(None)
        assert order == ["a", "c"]
        outcomes = {r.stage: r.outcome for r in lad.last_results}
        assert outcomes == {"a": RELEASED, "b": FAILED, "c": RELEASED}

    def test_timed_out_stage_is_abandoned_not_fatal(self):
        release = threading.Event()
        lad = AbortLadder(
            _Stage("slow", lambda: release.wait(30), timeout=0.15),
            _Stage("after"),
        )
        t0 = time.monotonic()
        lad(None)
        assert time.monotonic() - t0 < 5.0
        outcomes = {r.stage: r.outcome for r in lad.last_results}
        assert outcomes["slow"] == TIMED_OUT
        assert outcomes["after"] == RELEASED  # the ladder kept going
        release.set()

    def test_escalate_skips_remaining_rungs(self):
        lad = AbortLadder(
            _Stage("first"),
            _Stage("give_up", lambda: (_ for _ in ()).throw(
                EscalateAbort("no in-process path"))),
            _Stage("never"),
        )
        lad(None)
        outcomes = {r.stage: r.outcome for r in lad.last_results}
        assert outcomes == {
            "first": RELEASED, "give_up": ESCALATE, "never": SKIPPED,
        }

    def test_plain_callables_and_compose_flatten_into_rungs(self):
        seen = []

        def plugin_a(state):
            seen.append("a")
            return state

        def plugin_b(state):
            seen.append("b")
            return state

        lad = AbortLadder(Compose(plugin_a, plugin_b), ClearJaxCaches())
        assert [s.name for s in lad.stages] == [
            "plugin_a", "plugin_b", "jax_caches",
        ]
        lad(None)
        assert seen == ["a", "b"]
        assert all(r.outcome == RELEASED for r in lad.last_results)

    def test_fn_stage_counts_as_plain_plugin_when_called_directly(self):
        calls = []
        stage = FnStage(lambda s: calls.append(s), name="legacy")
        assert stage("x") == "x"  # plugin-compatible direct call
        assert calls == ["x"]

    def test_take_results_drains_once(self):
        lad = AbortLadder(_Stage("a"))
        lad(None)
        assert len(lad.take_results()) == 1
        assert lad.take_results() == []

    def test_telemetry_counts_stage_outcomes(self):
        reg = get_registry()
        before = reg.value_of(
            "tpurx_abort_stage_outcomes_total",
            {"stage": "tele", "outcome": RELEASED},
        )
        AbortLadder(_Stage("tele"))(None)
        after = reg.value_of(
            "tpurx_abort_stage_outcomes_total",
            {"stage": "tele", "outcome": RELEASED},
        )
        assert after == before + 1

    def test_shrink_stage_gated_off_by_default(self, monkeypatch):
        monkeypatch.delenv("TPURX_SHRINK_MESH", raising=False)
        assert not ShrinkMeshStage().applicable()
        monkeypatch.setenv("TPURX_SHRINK_MESH", "1")
        assert ShrinkMeshStage().applicable()
        assert ShrinkMeshStage(enabled=True).applicable()

    def test_fingerprint_stage_gated_until_bound(self):
        stage = FingerprintStage()
        assert not stage.applicable()
        lad = AbortLadder(stage)
        lad(None)
        assert lad.last_results[0].outcome == SKIPPED


class _FakeOps:
    def __init__(self):
        self.published = []

    def record_fingerprint(self, iteration, rank, tail):
        self.published.append((iteration, rank, list(tail)))


class TestFingerprint:
    def test_ring_wraps_and_keeps_newest(self):
        tail = DispatchTail(capacity=4)
        for i in range(9):
            tail.record(f"op{i}")
        snap = tail.snapshot()
        assert [e["op"] for e in snap] == ["op5", "op6", "op7", "op8"]
        assert snap[-1]["seq"] == 9
        assert all(e["age_ms"] >= 0 for e in snap)

    def test_shm_tail_cross_attach(self):
        tail = DispatchTail.create(capacity=4)
        if tail.name is None:
            pytest.skip("shm unavailable on this host")
        tail.record("matmul_step")
        try:
            got = read_tail(tail.name)
            assert [e["op"] for e in got] == ["matmul_step"]
        finally:
            tail.close()

    def test_attach_rejects_non_arena(self):
        from tpu_resiliency.utils.shm import create_shm, unlink_shm

        shm = create_shm(256)
        try:
            with pytest.raises(ValueError):
                DispatchTail.attach(shm.name)
        finally:
            unlink_shm(shm)
            shm.close()

    def test_stage_publishes_tail(self):
        ops = _FakeOps()
        tail = DispatchTail(capacity=4)
        from tpu_resiliency.inprocess import fingerprint as fp

        prev = fp.install_tail(tail)
        try:
            tail.record("collective_x")
            stage = FingerprintStage(ops, rank=3, iteration_fn=lambda: 7)
            AbortLadder(stage)(None)
        finally:
            fp.install_tail(prev)
        assert len(ops.published) == 1
        iteration, rank, published = ops.published[0]
        assert (iteration, rank) == (7, 3)
        assert published[0]["op"] == "collective_x"

    def test_parse_fingerprints_tolerates_garbage(self):
        raw = (
            b'{"rank": 0, "tail": [{"op": "x", "age_ms": 1, "seq": 1}]}\n'
            b"not json\n"
            b'{"rank": "bad"}\n'
            b'{"rank": 1, "tail": []}\n'
        )
        got = parse_fingerprints(raw)
        assert set(got) == {0, 1}
        assert got[0][0]["op"] == "x"
        assert parse_fingerprints(None) == {}

    def test_interruption_record_roundtrips_fingerprint(self):
        rec = InterruptionRecord(
            rank=2, interruption=Interruption.SOFT_TIMEOUT, message="stall",
            fingerprint=[{"op": "spin", "age_ms": 1234, "seq": 8}],
        )
        back = InterruptionRecord.from_json(rec.to_json())
        assert back.fingerprint == rec.fingerprint
        # records without one stay wire-compatible
        bare = InterruptionRecord.from_json(
            '{"rank": 0, "interruption": "exception"}'
        )
        assert bare.fingerprint == []


class TestAnalyzeFingerprints:
    def test_lagging_rank_named_with_in_flight_op(self):
        v = analyze_fingerprints({
            0: [{"op": "all_reduce", "age_ms": 120, "seq": 10}],
            1: [{"op": "all_reduce", "age_ms": 2500, "seq": 7}],
            2: [{"op": "all_reduce", "age_ms": 90, "seq": 10}],
        })
        assert v.category == "wedged_collective"
        assert v.culprit_ranks == [1]
        assert "all_reduce" in v.summary

    def test_divergent_rank_never_dispatched_the_op(self):
        v = analyze_fingerprints({
            0: [{"op": "all_reduce", "age_ms": 100, "seq": 10}],
            1: [{"op": "data_load", "age_ms": 150, "seq": 6}],
            2: [{"op": "all_reduce", "age_ms": 110, "seq": 10}],
        })
        assert v.culprit_ranks == [1]
        assert "never dispatched" in v.summary

    def test_missing_fingerprint_is_the_culprit(self):
        v = analyze_fingerprints({
            0: [{"op": "all_reduce", "age_ms": 100, "seq": 10}],
            1: [],
            2: [{"op": "all_reduce", "age_ms": 110, "seq": 10}],
        })
        assert v.culprit_ranks == [1]
        assert "no fingerprint" in v.summary

    def test_no_data_and_uniform_stall(self):
        assert analyze_fingerprints({0: [], 1: []}).category == "no_data"
        v = analyze_fingerprints({
            0: [{"op": "all_reduce", "age_ms": 900, "seq": 5}],
            1: [{"op": "all_reduce", "age_ms": 1000, "seq": 5}],
        })
        assert v.category == "collective_stall"
        assert v.culprit_ranks == []


class TestRetryPolicy:
    def test_exponential_delays_bounded(self):
        p = RetryPolicy(base_delay=0.1, max_delay=0.5, multiplier=2.0,
                        min_delay_fraction=1.0)
        assert [p.delay(n) for n in (1, 2, 3, 4, 5)] == [
            0.1, 0.2, 0.4, 0.5, 0.5,
        ]

    def test_jitter_stays_in_band(self):
        p = RetryPolicy(base_delay=1.0, multiplier=1.0, min_delay_fraction=0.5)
        for _ in range(200):
            assert 0.5 <= p.delay(1) <= 1.0

    def test_retrier_attempt_budget(self):
        sleeps = []
        r = Retrier("t_budget", RetryPolicy(max_attempts=3, base_delay=0.01),
                    sleep=sleeps.append)
        r.backoff(OSError("1"))
        r.backoff(OSError("2"))
        with pytest.raises(RetryExhausted) as ei:
            r.backoff(OSError("3"))
        assert len(sleeps) == 2
        assert isinstance(ei.value.last_exc, OSError)
        assert ei.value.attempts == 3

    def test_retrier_deadline_clamps_sleep(self):
        clock = [0.0]
        sleeps = []

        def fake_sleep(s):
            sleeps.append(s)
            clock[0] += s

        r = Retrier(
            "t_deadline",
            RetryPolicy(max_attempts=None, base_delay=10.0, max_delay=10.0,
                        min_delay_fraction=1.0, deadline=4.0),
            sleep=fake_sleep, clock=lambda: clock[0],
        )
        r.backoff()          # clamped to the 4s remaining
        assert sleeps == [4.0]
        with pytest.raises(RetryExhausted):
            r.backoff()      # budget spent

    def test_retry_call_and_telemetry(self):
        reg = get_registry()
        before = reg.value_of("tpurx_retry_attempts_total",
                              {"site": "t_call"})
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "done"

        out = retry_call(
            flaky, site="t_call",
            policy=RetryPolicy(max_attempts=5, base_delay=0.001),
            retry_on=(OSError,),
        )
        assert out == "done"
        after = reg.value_of("tpurx_retry_attempts_total", {"site": "t_call"})
        assert after == before + 3

    def test_retry_call_propagates_unlisted_exceptions(self):
        with pytest.raises(ValueError):
            retry_call(
                lambda: (_ for _ in ()).throw(ValueError("no")),
                site="t_prop", policy=RetryPolicy(max_attempts=3),
                retry_on=(OSError,),
            )
