"""tpurx-lint framework tests: per-rule firing/passing fixtures, suppression
discipline, baseline round-trip, and the tier-1 repo gate.

Fixture snippets are written into a throwaway tree mirroring the repo layout
(`<tmp>/tpu_resiliency/...`) because every rule scopes by repo-relative path.
"""

import json
import os
import textwrap
import time

import pytest

from tpurx_lint import run_lint
from tpurx_lint.baseline import Baseline
from tpurx_lint.registry import all_rules

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def lint_snippet(tmp_path, rel, code, rule=None, extra_files=()):
    """Write `code` at `<tmp>/<rel>` and lint it; returns finding list."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(code))
    for erel, ecode in extra_files:
        epath = tmp_path / erel
        epath.parent.mkdir(parents=True, exist_ok=True)
        epath.write_text(textwrap.dedent(ecode))
    result = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                      use_baseline=False,
                      rule_ids=[rule] if rule else None)
    return result.findings


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# rule registry basics
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_sixteen_rules_with_stable_ids(self):
        ids = [r.rule_id for r in all_rules()]
        assert ids == [f"TPURX{n:03d}" for n in range(1, 17)]

    def test_every_rule_documents_itself(self):
        for r in all_rules():
            assert r.name and r.rationale and r.scope, r.rule_id


# ---------------------------------------------------------------------------
# migrated bans (TPURX001-004): one firing + one passing case each
# ---------------------------------------------------------------------------

class TestBarePrint:
    def test_fires(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py",
                          "print('hi')\n", rule="TPURX001")
        assert rules_of(fs) == {"TPURX001"}

    def test_passes_logger_and_out_of_scope(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py",
                                "import logging\nlogging.info('hi')\n",
                                rule="TPURX001")
        # scripts outside the library may print
        assert not lint_snippet(tmp_path, "benchmarks/x.py", "print('hi')\n",
                                rule="TPURX001")


class TestRawCkptRead:
    def test_fires_on_rb_open_and_os_pread(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/checkpointing/x.py", """
            import os
            def f(p, fd):
                with open(p, "rb") as fh:
                    fh.read()
                os.pread(fd, 10, 0)
        """, rule="TPURX002")
        assert len(fs) == 2

    def test_passes_in_integrity_and_write_mode(self, tmp_path):
        assert not lint_snippet(
            tmp_path, "tpu_resiliency/checkpointing/integrity.py",
            'x = open("p", "rb")\n', rule="TPURX002")
        assert not lint_snippet(
            tmp_path, "tpu_resiliency/checkpointing/x.py",
            'x = open("p", "wb")\n', rule="TPURX002")


class TestWallClockStamp:
    def test_fires(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import time
            last_heartbeat = time.time()
        """, rule="TPURX003")
        assert rules_of(fs) == {"TPURX003"}

    def test_passes_non_stamp_and_quorum_home(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py",
                                "import time\nstarted = time.time()\n",
                                rule="TPURX003")
        assert not lint_snippet(tmp_path, "tpu_resiliency/ops/quorum.py",
                                "import time\nstamp = time.time()\n",
                                rule="TPURX003")


class TestFlatGather:
    def test_fires_on_loop_and_multiget(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            def f(store, world_size):
                out = [store.get(f"k/{r}") for r in range(2)]
                for r in range(world_size):
                    out.append(store.try_get(f"k/{r}"))
                store.multi_get([f"k/{r}" for r in range(world_size)])
                return out
        """, rule="TPURX004")
        assert len(fs) == 2  # loop-read + multi_get comprehension

    def test_passes_in_tree_helper(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/store/tree.py", """
            def f(store, world_size):
                return [store.get(f"k/{r}") for r in range(world_size)]
        """, rule="TPURX004")


# ---------------------------------------------------------------------------
# deep checkers (TPURX005-010)
# ---------------------------------------------------------------------------

class TestDeadlineDiscipline:
    def test_fires_on_unbounded_waits(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import subprocess
            def f(ev, t, proc):
                ev.wait()
                t.join()
                proc.communicate()
                subprocess.run(["x"])
        """, rule="TPURX005")
        assert len(fs) == 4

    def test_passes_with_bounds(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import asyncio
            import subprocess
            async def f(ev, t, proc, timeout):
                ev.wait(5.0)
                ev.wait(timeout=timeout)
                t.join(timeout=30)
                proc.communicate(timeout=10)
                subprocess.run(["x"], timeout=60)
                ",".join(["a", "b"])          # str.join has an argument
                await asyncio.wait_for(ev.wait(), timeout=1.0)
        """, rule="TPURX005")

    def test_timeout_none_is_unbounded(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py",
                          "def f(ev):\n    ev.wait(timeout=None)\n",
                          rule="TPURX005")
        assert len(fs) == 1

    def test_fires_on_raw_socket_recv_without_bound(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            def f(sock, conn):
                a = sock.recv(4096)
                b = conn.recv_into(bytearray(16))
                return a, b
        """, rule="TPURX005")
        assert len(fs) == 2
        assert all("socket wait blocks async raises" in f.message for f in fs)

    def test_passes_recv_with_deadline_intent_in_scope(self, tmp_path):
        # intent, not value: a finite settimeout / poll gate anywhere in the
        # enclosing function (or a timeout= kw on a recv wrapper) bounds it
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            def f(sock, conn, exchange, t):
                sock.settimeout(t)
                a = sock.recv(4096)
                if conn.poll(0.25):
                    b = conn.recv(16)
                c = exchange.recv(1, 2, timeout=t)
                return a, b, c
        """, rule="TPURX005")

    def test_recv_sanctioned_in_store_io_core(self, tmp_path):
        # store/client.py and store/mux.py ARE the interruptible I/O core:
        # their recv loops are quantum-sliced by construction
        assert not lint_snippet(tmp_path, "tpu_resiliency/store/client.py", """
            def f(sock):
                return sock.recv(4096)
        """, rule="TPURX005")

    def test_recv_bufsize_is_not_a_timeout(self, tmp_path):
        # the positional arg of recv is a byte count; it must not satisfy
        # the bound check the way a positional timeout does for wait()
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            def f(sock):
                return sock.recv(65536)
        """, rule="TPURX005")
        assert len(fs) == 1

    def test_create_connection_needs_timeout(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import socket
            def f():
                a = socket.create_connection(("h", 1))
                b = socket.create_connection(("h", 1), timeout=2.0)
                return a, b
        """, rule="TPURX005")
        assert len(fs) == 1


class TestAbortPathSafety:
    def test_fires_in_abort_stage_and_signal_handler(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/inprocess/x.py", """
            import signal
            import threading

            class AbortStage:
                pass

            class MyStage(AbortStage):
                def release(self, state=None):
                    self._helper()

                def _helper(self):
                    threading.Thread(target=print).start()

            def _handler(signum, frame):
                import subprocess
                subprocess.run(["cleanup"])

            signal.signal(signal.SIGTERM, _handler)
        """, rule="TPURX006")
        msgs = [f.message for f in fs]
        assert any("thread spawned" in m for m in msgs)
        assert any("signal handler" in m for m in msgs)

    def test_passes_bounded_stage(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/inprocess/x.py", """
            class AbortStage:
                pass

            class MyStage(AbortStage):
                def release(self, state=None):
                    state.proc.wait(timeout=5.0)
        """, rule="TPURX006")


class TestRetryDiscipline:
    def test_fires_on_hand_rolled_loop(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import time
            def f(connect):
                while True:
                    try:
                        return connect()
                    except OSError:
                        time.sleep(1.0)
        """, rule="TPURX007")
        assert rules_of(fs) == {"TPURX007"}

    def test_passes_poll_loop_and_retry_home(self, tmp_path):
        # a forever poll loop (no success escape in the try) is not a retry
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import time
            def monitor(tick):
                while True:
                    try:
                        tick()
                    except OSError:
                        pass
                    time.sleep(1.0)
        """, rule="TPURX007")
        assert not lint_snippet(tmp_path, "tpu_resiliency/utils/retry.py", """
            import time
            def f(connect):
                while True:
                    try:
                        return connect()
                    except OSError:
                        time.sleep(1.0)
        """, rule="TPURX007")


class TestThreadLifecycle:
    def test_fires_on_leaked_thread(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import threading
            def f():
                t = threading.Thread(target=print)
                t.start()
        """, rule="TPURX008")
        assert rules_of(fs) == {"TPURX008"}

    def test_passes_daemon_or_joined(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import threading
            def f():
                threading.Thread(target=print, daemon=True).start()
                t = threading.Thread(target=print)
                t.start()
                t.join(timeout=5.0)
        """, rule="TPURX008")

    def test_guarded_by_fires_outside_lock(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0  # guarded-by: _lock

                def bump(self):
                    self._n += 1
        """, rule="TPURX008")
        assert any("guarded-by" in f.message for f in fs)

    def test_guarded_by_passes_under_lock(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0  # guarded-by: _lock

                def bump(self):
                    with self._lock:
                        self._n += 1
        """, rule="TPURX008")


class TestExceptionHygiene:
    def test_fires_on_swallow_and_bare(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/inprocess/x.py", """
            def f(g):
                try:
                    g()
                except Exception:
                    pass
                try:
                    g()
                except:
                    raise
        """, rule="TPURX009")
        assert len(fs) == 2

    def test_passes_narrow_or_logged(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/inprocess/x.py", """
            import logging
            def f(g):
                try:
                    g()
                except OSError:
                    pass
                try:
                    g()
                except Exception as exc:
                    logging.warning("failed: %r", exc)
        """, rule="TPURX009")

    def test_swallow_allowed_outside_fault_tree(self, tmp_path):
        # integrations/ is not a fault-handling tree; only bare except fires
        assert not lint_snippet(
            tmp_path, "tpu_resiliency/integrations/x.py",
            "def f(g):\n    try:\n        g()\n    except Exception:\n        pass\n",
            rule="TPURX009")


_ENV_FIXTURE = [
    ("tpu_resiliency/utils/env.py", """
        class Knob:
            def __init__(self, name, type, default, doc):
                self.name = name
        FOO = Knob("TPURX_FOO", int, 1, "doc")
    """),
    ("docs/configuration.md", "| `TPURX_FOO` | int | `1` | doc |\n"),
]


class TestEnvRegistry:
    def test_fires_on_raw_read(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import os
            x = os.environ.get("TPURX_FOO", "1")
            y = os.getenv("TPURX_BAR")
            z = os.environ["TPURX_BAZ"]
            present = "TPURX_QUX" in os.environ
        """, rule="TPURX010", extra_files=_ENV_FIXTURE)
        assert len(fs) == 4

    def test_resolves_env_constant_idiom(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import os
            ENV_FOO = "TPURX_FOO"
            x = os.environ.get(ENV_FOO)
        """, rule="TPURX010", extra_files=_ENV_FIXTURE)
        assert len(fs) == 1

    def test_passes_registry_read_and_non_tpurx(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import os
            from .utils import env
            x = env.FOO.get()
            home = os.environ.get("HOME")
        """, rule="TPURX010", extra_files=_ENV_FIXTURE)

    def test_fires_on_direct_write(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import os
            os.environ["TPURX_FOO"] = "1"
            os.environ.setdefault("TPURX_BAR", "1")
            os.environ.pop("TPURX_BAZ", None)
            os.putenv("TPURX_QUX", "1")
            os.environ.update({"TPURX_QUUX": "1"})
        """, rule="TPURX010", extra_files=_ENV_FIXTURE)
        assert len([f for f in fs if "direct os.environ write" in f.message]) \
            == 5

    def test_policy_package_is_sanctioned_writer(self, tmp_path):
        assert not lint_snippet(
            tmp_path, "tpu_resiliency/policy/actuator.py", """
                import os
                os.environ["TPURX_FOO"] = "1"
            """, rule="TPURX010", extra_files=_ENV_FIXTURE)

    def test_identity_republication_is_exempt(self, tmp_path):
        # the launcher restamps rank identity after a mesh shrink; children
        # inherit it through the real environment, so the write is legal
        assert not lint_snippet(
            tmp_path, "tpu_resiliency/inprocess/state.py", """
                import os
                os.environ["TPURX_RANK"] = "0"
                os.environ["TPURX_WORLD_SIZE"] = "4"
            """, rule="TPURX010", extra_files=_ENV_FIXTURE)

    def test_write_through_constant_idiom_fires(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import os
            ENV_FOO = "TPURX_FOO"
            os.environ[ENV_FOO] = "1"
        """, rule="TPURX010", extra_files=_ENV_FIXTURE)
        assert len(fs) == 1 and "direct os.environ write" in fs[0].message

    def test_repurposed_exempt_key_loses_waiver(self, tmp_path):
        # TPURX_RANK declared as a plain tuning knob (not identity-group,
        # no publisher doc) -> the WRITE_EXEMPT entry no longer qualifies
        fs = lint_snippet(
            tmp_path, "tpu_resiliency/utils/env.py", """
                class Knob:
                    def __init__(self, name, type, default, doc, group="g"):
                        self.name = name
                RANK = Knob("TPURX_RANK", int, 0, "doc", group="tuning")
            """, rule="TPURX010",
            extra_files=[("docs/configuration.md", "`TPURX_RANK`\n")])
        assert any("no longer qualifies" in f.message for f in fs)

    def test_undocumented_knob_fails(self, tmp_path):
        fs = lint_snippet(
            tmp_path, "tpu_resiliency/utils/env.py", """
                class Knob:
                    def __init__(self, name, type, default, doc):
                        self.name = name
                FOO = Knob("TPURX_FOO", int, 1, "doc")
                BAR = Knob("TPURX_BAR", int, 2, "doc")
            """, rule="TPURX010",
            extra_files=[("docs/configuration.md", "only `TPURX_FOO` here\n")])
        assert any("TPURX_BAR" in f.message and "not documented" in f.message
                   for f in fs)

    def test_duplicate_declaration_fails(self, tmp_path):
        fs = lint_snippet(
            tmp_path, "tpu_resiliency/utils/env.py", """
                class Knob:
                    def __init__(self, name, type, default, doc):
                        self.name = name
                A = Knob("TPURX_FOO", int, 1, "doc")
                B = Knob("TPURX_FOO", int, 2, "doc")
            """, rule="TPURX010",
            extra_files=[("docs/configuration.md", "`TPURX_FOO`\n")])
        assert any("declared more than once" in f.message for f in fs)


# ---------------------------------------------------------------------------
# whole-program tier (TPURX011-013) — see test_lockorder_analysis.py for the
# deep call-graph/lock-order fixtures; these are the one-firing/one-passing
# cases the rule-addition checklist requires
# ---------------------------------------------------------------------------

class TestLockOrder:
    def test_fires_on_intra_class_inversion(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._b:
                        with self._a:
                            pass
        """, rule="TPURX011")
        assert rules_of(fs) == {"TPURX011"}
        assert any("PLAUSIBLE" in f.message and "deadlock" in f.message
                   for f in fs)

    def test_passes_consistent_order(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._a:
                        with self._b:
                            pass
        """, rule="TPURX011")


class TestDeadlinePropagation:
    def test_fires_on_dead_and_dropped_deadline(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            class C:
                def join(self, timeout):
                    self._cv.wait()
        """, rule="TPURX012")
        msgs = [f.message for f in fs]
        assert any("never reads it" in m for m in msgs)
        assert any("drops it" in m for m in msgs)

    def test_passes_threaded_deadline(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            class C:
                def join(self, timeout):
                    self._cv.wait(timeout=timeout)
        """, rule="TPURX012")

    def test_fires_on_call_site_drop(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            def blocking_helper(timeout=None):
                ev().wait(timeout=timeout)

            def outer(deadline):
                x = deadline  # read, so no dead-deadline finding
                blocking_helper()
        """, rule="TPURX012")
        assert len(fs) == 1
        assert "stops propagating" in fs[0].message

    def test_passes_call_site_bound(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            def blocking_helper(timeout=None):
                ev().wait(timeout=timeout)

            def outer(deadline):
                blocking_helper(timeout=deadline)
        """, rule="TPURX012")


class TestStoreKeyLifecycle:
    def test_fires_on_undeleted_round_key(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/store/proto.py", """
            def publish(store, round_no, rank):
                store.set(f"round/{round_no}/r{rank}", b"1")
        """, rule="TPURX013")
        assert rules_of(fs) == {"TPURX013"}
        assert "round" in fs[0].message

    def test_passes_with_delete_path_and_singleton(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/store/proto.py", """
            def publish(store, round_no, rank):
                store.set(f"round/{round_no}/r{rank}", b"1")
                store.set("round_singleton", b"1")

            def gc(store, round_no, rank):
                store.delete(f"round/{round_no}/r{rank}")
        """, rule="TPURX013")

    def test_append_on_fixed_key_still_fires(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/store/proto.py", """
            def log(store, rank):
                store.append("audit_log", f"{rank},")
        """, rule="TPURX013")
        assert rules_of(fs) == {"TPURX013"}


class TestRawCollective:
    def test_fires_on_allgather_and_lax(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import jax
            from jax import lax
            from jax.experimental import multihost_utils

            def f(x, axis):
                vals = multihost_utils.process_allgather(x)
                a = lax.pmax(x, axis)
                b = jax.lax.ppermute(x, axis, perm=[(0, 1)])
                return vals, a, b
        """, rule="TPURX014")
        assert rules_of(fs) == {"TPURX014"}
        assert len(fs) == 3
        msgs = " ".join(f.message for f in fs)
        assert "ResilientCollective" in msgs

    def test_passes_in_wrapper_home_and_quorum_lane(self, tmp_path):
        # parallel/collectives.py is the sanctioned home for raw collectives
        assert not lint_snippet(
            tmp_path, "tpu_resiliency/parallel/collectives.py", """
                from jax import lax
                from jax.experimental import multihost_utils

                def f(x, axis):
                    return multihost_utils.process_allgather(x), lax.pmax(x, axis)
            """, rule="TPURX014")
        # ops/quorum.py's jitted detection lane is allowlisted
        assert not lint_snippet(tmp_path, "tpu_resiliency/ops/quorum.py", """
            import jax

            def f(x, axis):
                return jax.lax.pmax(x, axis)
        """, rule="TPURX014")

    def test_passes_non_collective_lax_and_out_of_scope(self, tmp_path):
        # lax math primitives are not collectives
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            from jax import lax

            def f(x):
                return lax.cumsum(x, axis=0)
        """, rule="TPURX014")
        # scripts outside the library may call raw collectives
        assert not lint_snippet(tmp_path, "benchmarks/x.py", """
            from jax.experimental import multihost_utils

            def f(x):
                return multihost_utils.process_allgather(x)
        """, rule="TPURX014")


class TestRawDeviceRead:
    def test_fires_on_raw_d2h(self, tmp_path):
        fs = lint_snippet(
            tmp_path, "tpu_resiliency/checkpointing/capture.py", """
            import jax

            def grab(tree, shard):
                shard.data.copy_to_host_async()
                return jax.device_get(tree)
        """, rule="TPURX015")
        assert rules_of(fs) == {"TPURX015"}
        assert len(fs) == 2
        assert "staging" in " ".join(f.message for f in fs)

    def test_passes_in_staging_layer_and_out_of_scope(self, tmp_path):
        # staging.py and device_digest.py are the sanctioned touchpoints
        for home in (
            "tpu_resiliency/checkpointing/async_ckpt/staging.py",
            "tpu_resiliency/checkpointing/async_ckpt/device_digest.py",
        ):
            assert not lint_snippet(tmp_path, home, """
                import jax

                def kick(shard):
                    shard.data.copy_to_host_async()
                    return jax.device_get(shard.data)
            """, rule="TPURX015")
        # non-checkpoint code may read devices freely
        assert not lint_snippet(tmp_path, "tpu_resiliency/health/probe.py", """
            import jax

            def probe(x):
                return jax.device_get(x)
        """, rule="TPURX015")

    def test_sanctioned_kick_passes(self, tmp_path):
        assert not lint_snippet(
            tmp_path, "tpu_resiliency/checkpointing/local/cap.py", """
            from ..async_ckpt.staging import async_d2h

            def grab(shards):
                async_d2h(s.data for s in shards)
        """, rule="TPURX015")


class TestWallClockDuration:
    def test_fires_on_direct_subtraction(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import time

            def f(t0):
                return time.time() - t0
        """, rule="TPURX016")
        assert rules_of(fs) == {"TPURX016"}

    def test_fires_on_assigned_name_used_in_subtraction(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import time

            def f(stamp):
                now = time.time()
                return now - stamp
        """, rule="TPURX016")
        assert len(fs) == 1

    def test_fires_on_datetime_now(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import datetime

            def f(started):
                return datetime.datetime.now() - started
        """, rule="TPURX016")
        assert rules_of(fs) == {"TPURX016"}

    def test_passes_monotonic_and_labels(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import time

            def f(t0):
                dur = time.monotonic_ns() - t0
                return {"dur": dur, "ts": time.time()}
        """, rule="TPURX016")

    def test_wall_name_in_one_function_does_not_taint_another(self, tmp_path):
        # `now` is wall-clock in f but monotonic in g: only f may fire
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            import time

            def f(t):
                now = time.time()
                return now - t

            def g(t):
                now = time.monotonic()
                return now - t
        """, rule="TPURX016")
        assert len(fs) == 1

    def test_allowlisted_file_and_out_of_scope_pass(self, tmp_path):
        snippet = """
            import time

            def age(m):
                return time.time() - m.ts
        """
        assert not lint_snippet(
            tmp_path, "tpu_resiliency/attribution/trace_analyzer.py",
            snippet, rule="TPURX016")
        assert not lint_snippet(
            tmp_path, "benchmarks/x.py", snippet, rule="TPURX016")


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

class TestSuppressions:
    def test_same_line_suppression_with_reason(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            def f(ev):
                ev.wait()  # tpurx: disable=TPURX005 -- sentinel always arrives
        """, rule="TPURX005")

    def test_comment_above_covers_next_line(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            def f(ev):
                # tpurx: disable=TPURX005 -- sentinel always arrives
                ev.wait()
        """, rule="TPURX005")

    def test_suppression_without_reason_is_a_finding(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            def f(ev):
                ev.wait()  # tpurx: disable=TPURX005
        """)
        assert "TPURX900" in rules_of(fs)
        # and the original finding is NOT suppressed by a reasonless directive
        assert "TPURX005" in rules_of(fs)

    def test_file_scope_suppression(self, tmp_path):
        assert not lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            # tpurx: disable-file=TPURX001 -- argparse CLI, stdout is the interface
            print("usage: ...")
            print("more")
        """, rule="TPURX001")

    def test_wrong_rule_suppression_does_not_mask(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            def f(ev):
                ev.wait()  # tpurx: disable=TPURX001 -- wrong rule entirely
        """, rule="TPURX005")
        assert rules_of(fs) == {"TPURX005"}

    def test_malformed_rule_id_is_a_finding(self, tmp_path):
        fs = lint_snippet(tmp_path, "tpu_resiliency/mod.py", """
            x = 1  # tpurx: disable=NOTARULE -- whatever
        """)
        assert "TPURX900" in rules_of(fs)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

class TestBaseline:
    def _write_offender(self, tmp_path):
        mod = tmp_path / "tpu_resiliency" / "mod.py"
        mod.parent.mkdir(parents=True, exist_ok=True)
        mod.write_text("def f(ev):\n    ev.wait()\n")
        return mod

    def test_round_trip(self, tmp_path):
        self._write_offender(tmp_path)
        result = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                          use_baseline=False, rule_ids=["TPURX005"])
        assert len(result.findings) == 1

        bpath = str(tmp_path / "baseline.json")
        bl = Baseline.from_findings(result.findings, bpath)
        for e in bl.entries:
            e.justification = "grandfathered: pre-lint wait"
        bl.save()
        reloaded = Baseline.load(bpath)
        assert [e.key() for e in reloaded.entries] == [e.key() for e in bl.entries]
        assert not reloaded.unjustified()

        gated = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                         baseline_path=bpath, rule_ids=["TPURX005"])
        assert not gated.findings and len(gated.baselined) == 1

    def test_baseline_keys_on_content_not_line_number(self, tmp_path):
        mod = self._write_offender(tmp_path)
        bpath = str(tmp_path / "baseline.json")
        result = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                          use_baseline=False, rule_ids=["TPURX005"])
        bl = Baseline.from_findings(result.findings, bpath)
        for e in bl.entries:
            e.justification = "grandfathered"
        bl.save()
        # unrelated edit above the offender moves its line number
        mod.write_text("import os\n\n\ndef f(ev):\n    ev.wait()\n")
        gated = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                         baseline_path=bpath, rule_ids=["TPURX005"])
        assert not gated.findings and len(gated.baselined) == 1
        # but editing the offending line itself resurfaces the finding
        mod.write_text("def f(ev):\n    ev.wait()  # now touched\n")
        gated = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                         baseline_path=bpath, rule_ids=["TPURX005"])
        assert len(gated.findings) == 1

    def test_unjustified_and_stale_entries_reported(self, tmp_path):
        self._write_offender(tmp_path)
        bpath = str(tmp_path / "baseline.json")
        with open(bpath, "w") as f:
            json.dump({"entries": [
                {"rule": "TPURX005", "path": "tpu_resiliency/mod.py",
                 "symbol": "ev.wait()", "justification": ""},
                {"rule": "TPURX005", "path": "tpu_resiliency/gone.py",
                 "symbol": "ev.wait()", "justification": "was removed"},
            ]}, f)
        result = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                          baseline_path=bpath)
        assert len(result.unjustified_baseline) == 1
        assert len(result.stale_baseline) == 1


# ---------------------------------------------------------------------------
# the repo gate (tier-1): zero non-baselined findings, fast, clean baseline
# ---------------------------------------------------------------------------

class TestRepoGate:
    @pytest.fixture(scope="class")
    def repo_result(self):
        # the gate lints the linter too (self-check), with the whole-program
        # tier enabled and --jobs auto — exactly what CI runs
        t0 = time.monotonic()
        result = run_lint(root=REPO, jobs="auto")
        result.elapsed = time.monotonic() - t0
        return result

    def test_zero_non_baselined_findings(self, repo_result):
        assert not repo_result.parse_errors, repo_result.parse_errors
        assert not repo_result.findings, "\n".join(
            f"{f.location()}: {f.rule} {f.message}" for f in repo_result.findings)

    def test_baseline_entries_all_justified_and_live(self, repo_result):
        assert not repo_result.unjustified_baseline, [
            e.key() for e in repo_result.unjustified_baseline]
        assert not repo_result.stale_baseline, [
            e.key() for e in repo_result.stale_baseline]

    def test_full_repo_lint_perf_floor(self, repo_result):
        # PR 8's per-file-only run measured 3.8s; the whole-program tier
        # (symbol table + call graph + 3 interprocedural rules) must stay
        # within 2x that with --jobs auto (measured ~6.0s single-core).
        # Bound carries ~2.5x slack for loaded CI hosts.
        assert repo_result.elapsed < 19.0, f"{repo_result.elapsed:.1f}s"

    def test_lints_itself(self, repo_result):
        # self-check: the tpurx_lint package is part of the default gate
        from tpurx_lint.engine import DEFAULT_PATHS
        assert "tpurx_lint" in DEFAULT_PATHS

    def test_cli_json_output(self):
        import subprocess
        import sys
        out = subprocess.run(
            [sys.executable, "-m", "tpurx_lint", "--format=json"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
        data = json.loads(out.stdout)
        assert data["ok"] is True
        assert data["findings"] == []


# ---------------------------------------------------------------------------
# SARIF output
# ---------------------------------------------------------------------------

# The structural subset of the SARIF 2.1.0 schema that CI annotators rely
# on: required top-level fields, driver rules with ids, results with ruleId/
# message/locations/regions.  (The full OASIS schema is ~500KB; this captures
# every property the spec marks `required` on the objects we emit.)
SARIF_21_SUBSET_SCHEMA = {
    "type": "object",
    "required": ["version", "runs"],
    "properties": {
        "version": {"const": "2.1.0"},
        "$schema": {"type": "string"},
        "runs": {
            "type": "array", "minItems": 1,
            "items": {
                "type": "object",
                "required": ["tool", "results"],
                "properties": {
                    "tool": {
                        "type": "object", "required": ["driver"],
                        "properties": {"driver": {
                            "type": "object", "required": ["name"],
                            "properties": {
                                "name": {"type": "string"},
                                "rules": {"type": "array", "items": {
                                    "type": "object", "required": ["id"],
                                }},
                            },
                        }},
                    },
                    "results": {"type": "array", "items": {
                        "type": "object",
                        "required": ["message"],
                        "properties": {
                            "ruleId": {"type": "string"},
                            "level": {"enum": ["none", "note", "warning",
                                               "error"]},
                            "message": {"type": "object",
                                        "required": ["text"]},
                            "locations": {"type": "array", "items": {
                                "type": "object",
                                "properties": {"physicalLocation": {
                                    "type": "object",
                                    "properties": {
                                        "artifactLocation": {
                                            "type": "object",
                                            "properties": {"uri": {
                                                "type": "string"}},
                                        },
                                        "region": {
                                            "type": "object",
                                            "properties": {"startLine": {
                                                "type": "integer",
                                                "minimum": 1}},
                                        },
                                    },
                                }},
                            }},
                        },
                    }},
                },
            },
        },
    },
}


class TestSarif:
    def _render(self, tmp_path):
        from tpurx_lint.sarif import render
        mod = tmp_path / "tpu_resiliency" / "mod.py"
        mod.parent.mkdir(parents=True, exist_ok=True)
        mod.write_text("def f(ev):\n    ev.wait()\n")
        result = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                          use_baseline=False)
        return render(result, all_rules(), str(tmp_path))

    def test_validates_against_sarif_210_schema(self, tmp_path):
        jsonschema = pytest.importorskip("jsonschema")
        log = self._render(tmp_path)
        jsonschema.validate(log, SARIF_21_SUBSET_SCHEMA)
        assert log["version"] == "2.1.0"
        assert "sarif-schema-2.1.0" in log["$schema"]

    def test_findings_carry_stable_fingerprints(self, tmp_path):
        log = self._render(tmp_path)
        results = log["runs"][0]["results"]
        assert any(r["ruleId"] == "TPURX005" for r in results)
        for r in results:
            assert r["partialFingerprints"]["tpurxContentKey/v1"]
        # fingerprint keys on content, not line: re-render after a shift
        mod = tmp_path / "tpu_resiliency" / "mod.py"
        mod.write_text("import os\n\ndef f(ev):\n    ev.wait()\n")
        result2 = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                           use_baseline=False)
        from tpurx_lint.sarif import render
        log2 = render(result2, all_rules(), str(tmp_path))
        fp = {r["partialFingerprints"]["tpurxContentKey/v1"]
              for r in log["runs"][0]["results"] if r["ruleId"] == "TPURX005"}
        fp2 = {r["partialFingerprints"]["tpurxContentKey/v1"]
               for r in log2["runs"][0]["results"] if r["ruleId"] == "TPURX005"}
        assert fp == fp2

    def test_cli_sarif_output(self):
        import subprocess
        import sys
        out = subprocess.run(
            [sys.executable, "-m", "tpurx_lint", "tpurx_lint/",
             "--format=sarif"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
        data = json.loads(out.stdout)
        assert data["version"] == "2.1.0"
        assert data["runs"][0]["tool"]["driver"]["name"] == "tpurx-lint"


# ---------------------------------------------------------------------------
# parallel engine
# ---------------------------------------------------------------------------

class TestParallelJobs:
    def test_jobs_equals_serial_findings(self, tmp_path):
        for i in range(6):
            mod = tmp_path / "tpu_resiliency" / f"m{i}.py"
            mod.parent.mkdir(parents=True, exist_ok=True)
            mod.write_text(
                f"def f{i}(ev):\n    ev.wait()\n    print('x')\n")
        serial = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                          use_baseline=False, jobs=1)
        par = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                       use_baseline=False, jobs=3)
        key = lambda fs: sorted((f.rule, f.path, f.line) for f in fs)  # noqa: E731
        assert key(par.findings) == key(serial.findings)
        assert len(serial.findings) == 12  # wait + print per module

    def test_suppressions_apply_across_jobs(self, tmp_path):
        mod = tmp_path / "tpu_resiliency" / "m.py"
        mod.parent.mkdir(parents=True, exist_ok=True)
        mod.write_text(
            "def f(ev):\n"
            "    ev.wait()  # tpurx: disable=TPURX005 -- bounded by caller\n")
        par = run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                       use_baseline=False, jobs=2)
        assert not par.findings

    def test_resolve_jobs(self):
        from tpurx_lint.engine import resolve_jobs
        assert resolve_jobs(None) == 1
        assert resolve_jobs(4) == 4
        assert resolve_jobs("auto") >= 1
        assert resolve_jobs(0) >= 1
