"""MCP server, webhook notifier, coalescing, funnel-wired launcher tests."""

import io
import json
import threading
import time

import pytest

from tpu_resiliency.attribution.mcp_server import handle_request, serve_stdio
from tpu_resiliency.attribution.base import AttributionResult
from tpu_resiliency.attribution.notify import WebhookNotifier, format_verdict


def _rpc(method, params=None, msg_id=1):
    return {"jsonrpc": "2.0", "id": msg_id, "method": method, "params": params or {}}


class TestMcpServer:
    def test_initialize_and_list(self):
        resp = handle_request(_rpc("initialize"))
        assert resp["result"]["serverInfo"]["name"] == "tpurx-attribution"
        assert handle_request({"jsonrpc": "2.0", "method": "notifications/initialized"}) is None
        tools = handle_request(_rpc("tools/list"))["result"]["tools"]
        assert {t["name"] for t in tools} == {
            "analyze_log", "analyze_trace", "analyze_combined",
        }

    def test_call_analyze_log(self):
        resp = handle_request(
            _rpc("tools/call", {
                "name": "analyze_log",
                "arguments": {"text": "RESOURCE_EXHAUSTED: allocating in hbm"},
            })
        )
        body = json.loads(resp["result"]["content"][0]["text"])
        assert body["category"] == "oom_hbm"
        assert body["should_resume"] is False
        assert resp["result"]["isError"] is False

    def test_call_analyze_trace(self):
        markers = {
            "0": {"rank": 0, "iteration": 0, "step": 10, "ts": time.time()},
            "1": {"rank": 1, "iteration": 0, "step": 5, "ts": time.time()},
        }
        resp = handle_request(
            _rpc("tools/call", {"name": "analyze_trace", "arguments": {"markers": markers}})
        )
        body = json.loads(resp["result"]["content"][0]["text"])
        assert body["category"] == "lagging_rank"
        assert body["culprit_ranks"] == [1]

    def test_unknown_tool_is_tool_error(self):
        resp = handle_request(_rpc("tools/call", {"name": "nope", "arguments": {}}))
        assert resp["result"]["isError"] is True

    def test_unknown_method(self):
        resp = handle_request(_rpc("bogus/method"))
        assert resp["error"]["code"] == -32601

    def test_stdio_roundtrip(self):
        stdin = io.StringIO(
            json.dumps(_rpc("initialize")) + "\n"
            + json.dumps(_rpc("tools/list", msg_id=2)) + "\n"
            + "not json\n"
        )
        stdout = io.StringIO()
        serve_stdio(stdin, stdout)
        lines = [json.loads(l) for l in stdout.getvalue().splitlines()]
        assert lines[0]["id"] == 1
        assert lines[1]["id"] == 2


class TestNotifier:
    def _result(self, category="oom_hbm", conf=0.95):
        return AttributionResult(
            category=category, confidence=conf, culprit_ranks=[3],
            summary="hbm exhausted", should_resume=False,
        )

    def test_posts_to_webhook(self):
        from http.server import BaseHTTPRequestHandler, HTTPServer

        received = []

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers["Content-Length"])
                received.append(json.loads(self.rfile.read(n)))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        server = HTTPServer(("127.0.0.1", 0), H)
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        url = f"http://127.0.0.1:{server.server_port}/hook"
        notifier = WebhookNotifier(url, job="llama-70b")
        out = notifier(self._result())
        server.shutdown()
        assert out.category == "oom_hbm"
        assert len(received) == 1
        assert "llama-70b" in received[0]["text"]
        assert "NO — operator action needed" in received[0]["text"]

    def test_category_filter(self):
        notifier = WebhookNotifier(
            "http://127.0.0.1:1/none", only_categories={"numerics"}
        )
        # oom_hbm filtered out -> no POST attempted -> no error logged path
        out = notifier(self._result())
        assert out is not None

    def test_failed_post_is_nonfatal(self):
        notifier = WebhookNotifier("http://127.0.0.1:1/dead", timeout=0.2)
        out = notifier(self._result())
        assert out.category == "oom_hbm"

    def test_format(self):
        text = format_verdict(self._result(), job="j1")
        assert "j1" in text and "oom_hbm" in text and "[3]" in text


def test_attrsvc_coalesces_concurrent_requests():
    import urllib.request

    from tpu_resiliency.services import attrsvc as svc

    server = svc.serve(host="127.0.0.1", port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{server.server_port}"
    text = "unique error for coalescing test: RESOURCE_EXHAUSTED hbm " + str(time.time())

    def post(out):
        req = urllib.request.Request(
            url + "/analyze", data=json.dumps({"text": text}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=15) as resp:
            out.append(json.loads(resp.read()))

    outs = []
    threads = [threading.Thread(target=post, args=(outs,)) for _ in range(6)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    server.shutdown()
    assert len(outs) == 6
    assert all(o["category"] == "oom_hbm" for o in outs)
