"""KV store substrate tests (mirrors reference store/barrier unit coverage)."""

import threading
import time

import pytest

from tpu_resiliency.store import (
    BarrierOverflow,
    BarrierTimeout,
    PrefixStore,
    StoreClient,
    StoreTimeout,
    barrier,
    reentrant_barrier,
)


def test_set_get(store):
    store.set("k", b"v")
    assert store.get("k") == b"v"
    assert store.try_get("missing") is None


def test_blocking_get_waits_for_set(store, store_server):
    result = {}

    def setter():
        time.sleep(0.2)
        other = StoreClient("127.0.0.1", store_server.port)
        other.set("late", b"arrived")
        other.close()

    t = threading.Thread(target=setter)
    t.start()
    result["v"] = store.get("late", timeout=5.0)
    t.join()
    assert result["v"] == b"arrived"


def test_get_timeout(store):
    with pytest.raises(StoreTimeout):
        store.get("never", timeout=0.2)


def test_add_atomic(store, store_server):
    n_threads, n_incr = 8, 50

    def worker():
        c = StoreClient("127.0.0.1", store_server.port)
        for _ in range(n_incr):
            c.add("counter", 1)
        c.close()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert store.add("counter", 0) == n_threads * n_incr


def test_append(store):
    assert store.append("log", b"a") == 1
    assert store.append("log", b"bc") == 3
    assert store.get("log") == b"abc"


def test_compare_set(store):
    # set-if-absent
    assert store.compare_set("cas", b"", b"first") == b"first"
    # wrong expectation -> returns current
    assert store.compare_set("cas", b"nope", b"second") == b"first"
    # correct expectation -> swapped
    assert store.compare_set("cas", b"first", b"second") == b"second"


def test_wait_and_check(store, store_server):
    store.set("a", b"1")
    assert store.check(["a"]) is True
    assert store.check(["a", "b"]) is False

    def setter():
        time.sleep(0.15)
        c = StoreClient("127.0.0.1", store_server.port)
        c.set("b", b"2")
        c.close()

    t = threading.Thread(target=setter)
    t.start()
    store.wait(["a", "b"], timeout=5.0)
    t.join()

    with pytest.raises(StoreTimeout):
        store.wait(["nothere"], timeout=0.2)


def test_wait_rides_out_server_restart(tmp_path):
    """A blocked WAIT survives the store host dying and returning: the
    client's sliced waits reconnect against the journal-restored server and
    release when the key finally lands.  This is the exact contract the
    event-driven rendezvous (joiners parked on k_done/k_open/k_count) and
    the chaos-store soak rely on."""
    from tpu_resiliency.store import StoreServer

    journal = str(tmp_path / "j.log")
    srv = StoreServer(host="127.0.0.1", port=0, journal_path=journal)
    srv.start_in_thread()
    port = srv.port
    waiter = StoreClient("127.0.0.1", port, timeout=30.0)
    released = {}

    def block():
        try:
            waiter.wait(["late/key"], timeout=25.0)
            released["ok"] = True
        except Exception as exc:  # noqa: BLE001
            released["err"] = exc

    t = threading.Thread(target=block)
    t.start()
    time.sleep(0.3)          # the wait is parked server-side
    srv.stop()               # store host "dies"
    time.sleep(0.3)
    srv2 = StoreServer(host="127.0.0.1", port=port, journal_path=journal)
    srv2.start_in_thread()   # journal-restored on the SAME endpoint
    try:
        setter = StoreClient("127.0.0.1", port)
        time.sleep(0.2)
        setter.set("late/key", b"v")
        t.join(timeout=20.0)
        assert released.get("ok"), released
        setter.close()
    finally:
        waiter.close()
        srv2.stop()


def test_delete_num_keys_list(store):
    store.multi_set({"p/x": b"1", "p/y": b"2", "q/z": b"3"})
    assert store.num_keys() == 3
    assert sorted(store.list_keys("p/")) == [b"p/x", b"p/y"]
    assert store.delete("p/x") is True
    assert store.delete("p/x") is False
    assert store.num_keys() == 2
    assert store.multi_get(["p/y", "q/z"]) == [b"2", b"3"]
    # per-key miss semantics: absent keys come back as None ENTRIES (the
    # old all-or-nothing None return could not name the missing key)
    assert store.multi_get(["p/y", "gone"]) == [b"2", None]
    assert store.multi_get(["gone", "also-gone"]) == [None, None]


def test_prefix_store(store):
    ps = PrefixStore("iter/0", store)
    ps.set("k", b"v")
    assert store.get("iter/0/k") == b"v"
    assert ps.get("k") == b"v"
    assert ps.add("c", 5) == 5
    nested = PrefixStore("inner", ps)
    nested.set("deep", b"d")
    assert store.get("iter/0/inner/deep") == b"d"
    assert sorted(ps.list_keys()) == [b"iter/0/c", b"iter/0/inner/deep", b"iter/0/k"]
    assert sorted(ps.list_keys("inner/")) == [b"iter/0/inner/deep"]


def _run_threads(fn, n):
    errors = []

    def wrapped(i):
        try:
            fn(i)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=wrapped, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return errors


def test_counting_barrier(store_server):
    world = 4
    release_times = []

    def member(i):
        c = StoreClient("127.0.0.1", store_server.port)
        time.sleep(0.05 * i)
        barrier(c, "b1", world, timeout=10.0)
        release_times.append(time.monotonic())
        c.close()

    errors = _run_threads(member, world)
    assert not errors
    assert len(release_times) == world
    assert max(release_times) - min(release_times) < 1.0


def test_barrier_overflow(store):
    barrier_world = 1
    barrier(store, "b2", barrier_world, timeout=5.0)
    with pytest.raises(BarrierOverflow):
        barrier(store, "b2", barrier_world, timeout=5.0)


def test_barrier_timeout_reports_missing(store):
    with pytest.raises(BarrierTimeout) as exc_info:
        barrier(store, "b3", 3, timeout=0.5)
    assert exc_info.value.arrived == 1
    assert exc_info.value.world_size == 3


def test_reentrant_barrier(store_server):
    world = 3

    def member(i):
        c = StoreClient("127.0.0.1", store_server.port)
        # rank 0 "restarts" and re-enters — must not deadlock or overflow
        reentrant_barrier(c, "rb", i, world, timeout=10.0)
        if i == 0:
            reentrant_barrier(c, "rb", i, world, timeout=10.0)
        c.close()

    errors = _run_threads(member, world)
    assert not errors


def test_failover_store_client(store_server):
    from tpu_resiliency.store import FailoverStoreClient

    # first endpoint dead, second is the live server -> transparent failover
    dead_port = 1  # nothing listens there
    c = FailoverStoreClient(
        [f"127.0.0.1:{dead_port}", f"127.0.0.1:{store_server.port}"],
        timeout=5.0, connect_timeout=6.0,
    )
    c.set("k", b"v")
    assert c.get("k") == b"v"
    c.close()


# -- on-disk journal ---------------------------------------------------------


def _journal_server(tmp_path, **kw):
    from tpu_resiliency.store import StoreServer

    return StoreServer(
        host="127.0.0.1", port=0, journal_path=str(tmp_path / "store.journal"), **kw
    ).start_in_thread()


def test_journal_restart_restores_state(tmp_path):
    from tpu_resiliency.store import StoreClient

    s1 = _journal_server(tmp_path)
    c = StoreClient("127.0.0.1", s1.port)
    c.set("rdzv/active_round", b"7")
    c.set("rdzv/cycle", b"12")
    c.add("counter", 5)
    c.append("log", b"abc")
    c.append("log", b"def")
    c.compare_set("cas", b"", b"v1")
    c.set("gone", b"x")
    c.delete("gone")
    c.close()
    s1.stop()

    s2 = _journal_server(tmp_path)
    assert s2.replayed_keys == 5
    c2 = StoreClient("127.0.0.1", s2.port)
    assert c2.get("rdzv/active_round") == b"7"
    assert c2.get("rdzv/cycle") == b"12"
    assert c2.get("counter") == b"5"
    assert c2.get("log") == b"abcdef"
    assert c2.get("cas") == b"v1"
    assert c2.try_get("gone") is None
    # mutations continue journaling after a restart
    assert c2.add("counter", 1) == 6
    c2.close()
    s2.stop()
    s3 = _journal_server(tmp_path)
    c3 = StoreClient("127.0.0.1", s3.port)
    assert c3.get("counter") == b"6"
    c3.close()
    s3.stop()


def test_journal_tolerates_torn_tail(tmp_path):
    from tpu_resiliency.store import StoreClient

    s1 = _journal_server(tmp_path)
    c = StoreClient("127.0.0.1", s1.port)
    c.set("good", b"kept")
    c.close()
    s1.stop()
    # crash mid-append: a partial record at the tail
    with open(tmp_path / "store.journal", "ab") as f:
        f.write(b"S" + (123456).to_bytes(4, "little") + b"partial-key-then-noth")
    s2 = _journal_server(tmp_path)
    c2 = StoreClient("127.0.0.1", s2.port)
    assert c2.get("good") == b"kept"
    assert s2.replayed_keys == 1
    # the torn tail was truncated: new writes land on a clean boundary
    c2.set("after", b"crash")
    c2.close()
    s2.stop()
    s3 = _journal_server(tmp_path)
    c3 = StoreClient("127.0.0.1", s3.port)
    assert c3.get("after") == b"crash" and c3.get("good") == b"kept"
    c3.close()
    s3.stop()


def test_journal_compaction_bounds_size(tmp_path):
    from tpu_resiliency.store import StoreClient

    s1 = _journal_server(tmp_path, journal_max_bytes=4096)
    c = StoreClient("127.0.0.1", s1.port)
    for i in range(500):
        c.set("hot", b"x" * 64 + str(i).encode())  # same key rewritten
    c.close()
    s1.stop()
    size = (tmp_path / "store.journal").stat().st_size
    assert size < 8192, size  # compacted: not 500 * ~80 bytes
    s2 = _journal_server(tmp_path)
    c2 = StoreClient("127.0.0.1", s2.port)
    assert c2.get("hot").endswith(b"499")
    c2.close()
    s2.stop()


def test_journal_lock_refuses_second_instance(tmp_path):
    from tpu_resiliency.store import StoreServer

    s1 = _journal_server(tmp_path)
    try:
        with pytest.raises(RuntimeError, match="locked by another store"):
            StoreServer(
                host="127.0.0.1", port=0,
                journal_path=str(tmp_path / "store.journal"),
            ).start_in_thread()
    finally:
        s1.stop()
    # lock released on stop: a successor starts fine
    s2 = _journal_server(tmp_path)
    s2.stop()


def test_journal_strip_prefixes(tmp_path):
    from tpu_resiliency.store import StoreClient, StoreServer

    s1 = _journal_server(tmp_path)
    c = StoreClient("127.0.0.1", s1.port)
    c.set("rdzv/shutdown", b"success")
    c.set("rdzv/shutdown/ack/nodeA", b"1")
    c.set("rdzv/cycle", b"9")
    c.close()
    s1.stop()
    s2 = StoreServer(
        host="127.0.0.1", port=0,
        journal_path=str(tmp_path / "store.journal"),
        journal_strip_prefixes=[b"rdzv/shutdown"],
    ).start_in_thread()
    c2 = StoreClient("127.0.0.1", s2.port)
    assert c2.try_get("rdzv/shutdown") is None
    assert c2.try_get("rdzv/shutdown/ack/nodeA") is None
    assert c2.get("rdzv/cycle") == b"9"
    c2.close()
    s2.stop()
    # the strip is journaled as deletes: a THIRD start without strip still
    # does not resurrect the flag
    s3 = _journal_server(tmp_path)
    c3 = StoreClient("127.0.0.1", s3.port)
    assert c3.try_get("rdzv/shutdown") is None
    c3.close()
    s3.stop()


def test_control_plane_restart_keeps_cycle_numbering(tmp_path):
    """The VERDICT ask: a restarted control plane continues cycle numbers."""
    from tpu_resiliency.fault_tolerance.rendezvous import (
        K_CYCLE,
        RendezvousHost,
        k_done,
    )
    from tpu_resiliency.store import StoreClient

    s1 = _journal_server(tmp_path)
    c = StoreClient("127.0.0.1", s1.port)
    host = RendezvousHost(c, min_nodes=1)
    host.bootstrap()
    host.open_round()   # round 0, cycle 0
    assert int(c.get(K_CYCLE)) == 1
    c.set(k_done(0), b"1")  # round 0 completed before the control plane died
    c.close()
    s1.stop()

    # control plane restarts from the journal
    s2 = _journal_server(tmp_path)
    c2 = StoreClient("127.0.0.1", s2.port)
    host2 = RendezvousHost(c2, min_nodes=1)
    host2.bootstrap()  # must be a no-op on restored state
    assert host2.current_round() == 0  # round pointer survived
    n = host2.open_round()
    assert n == 1       # advances past the completed round 0
    assert int(c2.get(K_CYCLE)) == 2  # cycle numbering continued, no reset
    c2.close()
    s2.stop()

    # a mid-round restart resumes the SAME open round (no spurious advance)
    s3 = _journal_server(tmp_path)
    c3 = StoreClient("127.0.0.1", s3.port)
    host3 = RendezvousHost(c3, min_nodes=1)
    host3.bootstrap()
    assert host3.open_round() == 1  # round 1 still open: resume it
    assert int(c3.get(K_CYCLE)) == 2
    c3.close()
    s3.stop()
