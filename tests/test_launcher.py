"""Launcher integration tests — the end-to-end in-job restart ring.

Reference analog: ``tests/fault_tolerance/unit/test_launcher.py`` +
``func/run_local_ddp_test_*`` scripts: launch the real launcher CLI as a
subprocess running a toy workload, inject crashes/hangs, assert automatic
re-rendezvous + restart-from-progress and clean final exit.
"""

import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

from tpu_resiliency.utils.env import disarm_platform_sitecustomize

REPO = Path(__file__).resolve().parent.parent
TOY = str(REPO / "tests" / "workloads" / "toy_train.py")


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_launcher(tmp_path, extra_env=None, nproc=2, max_restarts=3, timeout=90,
                 iters=15, expect_rc=0):
    port = free_port()
    env = dict(os.environ)
    disarm_platform_sitecustomize(env)
    env.update(
        {
            "TPURX_REPO": str(REPO),
            "TOY_ITERS": str(iters),
            "TOY_CKPT": str(tmp_path / "progress.txt"),
            # keep things snappy + no device probe in unit tests
            "TPURX_FT_ENABLE_DEVICE_HEALTH_CHECK": "0",
            "TPURX_FT_WORKLOAD_CHECK_INTERVAL": "0.1",
            "TPURX_FT_WORKERS_STOP_TIMEOUT": "3.0",
            "TPURX_FT_RDZV_ROUND_TIMEOUT": "30.0",
            "TPURX_PROFILING_FILE": str(tmp_path / "profiling.jsonl"),
        }
    )
    env.update(extra_env or {})
    cmd = [
        sys.executable, "-m", "tpu_resiliency.fault_tolerance.launcher",
        "--nnodes", "1", "--nproc-per-node", str(nproc),
        "--rdzv-endpoint", f"127.0.0.1:{port}",
        "--host-store", "--max-restarts", str(max_restarts),
        "--log-dir", str(tmp_path / "logs"),
        "--monitor-interval", "0.05",
        TOY,
    ]
    proc = subprocess.run(
        cmd, cwd=str(REPO), env=env, capture_output=True, text=True, timeout=timeout
    )
    if proc.returncode != expect_rc:
        print("STDOUT:", proc.stdout[-4000:])
        print("STDERR:", proc.stderr[-4000:])
    assert proc.returncode == expect_rc
    return proc, tmp_path / "progress.txt"


def test_clean_run_no_faults(tmp_path):
    proc, ckpt = run_launcher(tmp_path, iters=8)
    assert int(ckpt.read_text()) == 8
    assert "toy[0" in proc.stdout  # per-cycle logs teed through launcher


def test_restart_after_worker_crash(tmp_path):
    # rank 1 crashes at iter 5 of cycle 0; job restarts and completes
    proc, ckpt = run_launcher(tmp_path, extra_env={"TOY_FAIL": "0:1:5"}, iters=12)
    assert int(ckpt.read_text()) == 12
    assert "injecting crash" in proc.stdout
    # second cycle resumed from persisted progress, not from zero
    assert "cycle=1 starting at iter" in proc.stdout
    log_dir = tmp_path / "logs"
    assert (log_dir / "cycle_0.log").exists()
    assert (log_dir / "cycle_1.log").exists()


def test_restart_after_hang_detection(tmp_path):
    # rank 0 stops heartbeating at iter 4; monitor kills it; launcher restarts
    proc, ckpt = run_launcher(
        tmp_path,
        extra_env={
            "TOY_HANG": "0:0:4",
            "TPURX_FT_RANK_HEARTBEAT_TIMEOUT": "1.0",
            "TPURX_FT_INITIAL_RANK_HEARTBEAT_TIMEOUT": "10.0",
        },
        iters=10,
        timeout=120,
    )
    assert int(ckpt.read_text()) == 10
    assert "injecting hang" in proc.stdout
    # profiling recorded the hang in the monitor process and restart in launcher
    prof = (tmp_path / "profiling.jsonl").read_text()
    assert "hang_detected" in prof
    assert "failure_detected" in prof


def test_quorum_trip_restarts_cycle_before_heartbeat_timeout(tmp_path):
    """VERDICT r2 #1, in-job ring: a quorum trip sends
    WorkloadControlRequest(RestartWorkload) through the rank-monitor IPC and
    the launcher restarts the cycle NOW — the heartbeat timeout (set to an
    hour) never gets a chance to fire."""
    t0 = time.monotonic()
    proc, ckpt = run_launcher(
        tmp_path,
        extra_env={
            "TOY_QUORUM_HANG": "0:0:4",
            "JAX_PLATFORMS": "cpu",
            # the host heartbeat ring is deliberately glacial: detection can
            # only have come from the quorum tripwire
            "TPURX_FT_RANK_HEARTBEAT_TIMEOUT": "3600",
            "TPURX_FT_INITIAL_RANK_HEARTBEAT_TIMEOUT": "3600",
        },
        iters=10,
        timeout=120,
    )
    elapsed = time.monotonic() - t0
    assert int(ckpt.read_text()) == 10
    assert "injecting quorum-stall" in proc.stdout
    combined = proc.stdout + proc.stderr
    assert "in-workload restart request" in combined
    assert "ICI quorum" in combined
    assert elapsed < 100, elapsed
    prof = (tmp_path / "profiling.jsonl").read_text()
    assert "failure_detected" in prof


def test_restart_budget_exhausted(tmp_path):
    # rank 0 crashes at iter 0 of every cycle; 1 restart allowed -> rc 1
    env = {"TOY_FAIL": "0:0:0"}
    # crash in all cycles: reuse fail spec per cycle by cycling TOY_FAIL via
    # the workload reading its cycle -> instead crash unconditionally:
    env["TOY_FAIL"] = "999:0:0"  # won't fire; use hang-free permanent crash
    port = free_port()
    full_env = dict(os.environ)
    full_env.update(
        {
            "TPURX_REPO": str(REPO),
            "TOY_ITERS": "10",
            "TPURX_FT_ENABLE_DEVICE_HEALTH_CHECK": "0",
            "TPURX_FT_WORKERS_STOP_TIMEOUT": "2.0",
            "TPURX_FT_RDZV_ROUND_TIMEOUT": "20.0",
        }
    )
    crash_always = str(REPO / "tests" / "workloads" / "crash_always.py")
    proc = subprocess.run(
        [
            sys.executable, "-m", "tpu_resiliency.fault_tolerance.launcher",
            "--nnodes", "1", "--nproc-per-node", "1",
            "--rdzv-endpoint", f"127.0.0.1:{port}",
            "--host-store", "--max-restarts", "2",
            "--monitor-interval", "0.05",
            crash_always,
        ],
        cwd=str(REPO), env=full_env, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1
    assert proc.stderr.count("worker failure detected") == 3  # initial + 2 restarts


def test_progress_tracker_stops_crash_loop(tmp_path):
    """No progress across cycles -> early termination before budget is spent."""
    port = free_port()
    env = dict(os.environ)
    disarm_platform_sitecustomize(env)
    env.update(
        {
            "TPURX_REPO": str(REPO),
            "TPURX_FT_ENABLE_DEVICE_HEALTH_CHECK": "0",
            "TPURX_FT_WORKERS_STOP_TIMEOUT": "2.0",
            "TPURX_FT_MAX_NO_PROGRESS_CYCLES": "2",
            "TPURX_FT_PROGRESS_ITERATION_FILE": str(tmp_path / "progress.txt"),
            "TPURX_FT_RDZV_ROUND_TIMEOUT": "20.0",
        }
    )
    crash_always = str(REPO / "tests" / "workloads" / "crash_always.py")
    proc = subprocess.run(
        [
            sys.executable, "-m", "tpu_resiliency.fault_tolerance.launcher",
            "--nnodes", "1", "--nproc-per-node", "1",
            "--rdzv-endpoint", f"127.0.0.1:{port}",
            "--host-store", "--max-restarts", "10",
            "--monitor-interval", "0.05",
            crash_always,
        ],
        cwd=str(REPO), env=env, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1
    assert "terminating early: no progress" in proc.stderr
    # stopped after 2 no-progress cycles, well under the 10-restart budget
    assert proc.stderr.count("worker failure detected") <= 3


def test_ft_param_cli_overrides(tmp_path):
    from tpu_resiliency.fault_tolerance.launcher import build_agent, parse_args

    args = parse_args([
        "--nnodes", "1", "--nproc-per-node", "1",
        "--rdzv-endpoint", "127.0.0.1:1",
        "--ft-param", "rank_heartbeat_timeout=33.5",
        "--ft-param", "enable_device_health_check=false",
        "--ft-param", "rank_section_timeouts={step: 12}",
        "x.py",
    ])
    agent = build_agent(args)
    assert agent.cfg.rank_heartbeat_timeout == 33.5
    assert agent.cfg.enable_device_health_check is False
    assert agent.cfg.rank_section_timeouts == {"step": 12}
    with pytest.raises(SystemExit):
        build_agent(parse_args([
            "--nnodes", "1", "--rdzv-endpoint", "127.0.0.1:1",
            "--ft-param", "not_a_field=1", "x.py",
        ]))


def test_operator_flags_map_into_config():
    from tpu_resiliency.fault_tolerance.launcher import build_agent, parse_args

    args = parse_args([
        "--nnodes", "1", "--rdzv-endpoint", "127.0.0.1:1",
        "--worker-stop-signal", "SIGINT",
        "--term-signal", "SIGTERM",
        "--workers-stop-timeout", "3.5",
        "--restart-policy", "min-healthy",
        "--min-healthy-workers", "2",
        "--allow-heterogeneous",
        "--", "echo", "hi",
    ])
    agent = build_agent(args)
    assert agent.cfg.worker_stop_signal == "SIGINT"
    assert agent.cfg.term_signal == "SIGTERM"
    assert agent.cfg.workers_stop_timeout == 3.5
    assert agent.cfg.restart_policy == "min-healthy"
    assert agent.cfg.min_healthy_workers == 2
    assert agent.cfg.require_equal_slots is False


def test_unknown_stop_signal_rejected():
    from tpu_resiliency.fault_tolerance.launcher import build_agent, parse_args

    args = parse_args([
        "--nnodes", "1", "--rdzv-endpoint", "127.0.0.1:1",
        "--worker-stop-signal", "SIGNOPE", "--", "echo", "hi",
    ])
    with pytest.raises(SystemExit):
        build_agent(args)


class _FakeProc:
    def __init__(self, code):
        self._code = code

    def poll(self):
        return self._code


def _agent_with(policy, min_healthy, codes):
    from tpu_resiliency.fault_tolerance.config import FaultToleranceConfig
    from tpu_resiliency.fault_tolerance.launcher import (
        ElasticAgent, WorkerSpec, _Worker,
    )

    cfg = FaultToleranceConfig(
        restart_policy=policy, min_healthy_workers=min_healthy,
    )
    agent = ElasticAgent(
        cfg, WorkerSpec(cmd=["true"], nproc_per_node=len(codes)),
        store_addr="127.0.0.1", store_port=1,
    )
    agent.workers = [
        _Worker(local_rank=i, global_rank=i, proc=_FakeProc(c))
        for i, c in enumerate(codes)
    ]
    return agent


def test_workers_status_any_failed_policy():
    assert _agent_with("any-failed", -1, [0, None, 1])._workers_status() == "failed"
    assert _agent_with("any-failed", -1, [0, None])._workers_status() == "running"
    assert _agent_with("any-failed", -1, [0, 0])._workers_status() == "succeeded"


def test_workers_status_min_healthy_policy():
    # 3 workers, tolerate one loss (need 2 healthy)
    mk = lambda codes: _agent_with("min-healthy", 2, codes)._workers_status()
    assert mk([0, None, 1]) == "running"      # sidecar died, 2 healthy
    assert mk([None, 1, 1]) == "failed"       # below min healthy
    assert mk([0, 0, 1]) == "succeeded"       # done, enough zero-exits
    assert mk([None, None, None]) == "running"
