"""Multi-launcher integration: several agents (=nodes) over one store.

Reference analog: multi-agent func tests with hot spares
(``ft_rendezvous_barrier.py:1842-1865`` standby path).
"""

import os
import socket
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
TOY = str(REPO / "tests" / "workloads" / "toy_train.py")


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def base_env(tmp_path, iters=12):
    env = dict(os.environ)
    env.update(
        {
            "TPURX_REPO": str(REPO),
            "TOY_ITERS": str(iters),
            "TOY_CKPT": str(tmp_path / "progress.txt"),
            "TPURX_FT_ENABLE_DEVICE_HEALTH_CHECK": "0",
            "TPURX_FT_WORKLOAD_CHECK_INTERVAL": "0.1",
            "TPURX_FT_WORKERS_STOP_TIMEOUT": "3.0",
            "TPURX_FT_RDZV_ROUND_TIMEOUT": "30.0",
        }
    )
    return env


def launcher_cmd(port, nnodes, node_id, host_store=False, nproc=1, max_restarts=3):
    cmd = [
        sys.executable, "-m", "tpu_resiliency.fault_tolerance.launcher",
        "--nnodes", nnodes, "--nproc-per-node", str(nproc),
        "--rdzv-endpoint", f"127.0.0.1:{port}",
        "--node-id", node_id,
        "--max-restarts", str(max_restarts),
        "--monitor-interval", "0.05",
        TOY,
    ]
    if host_store:
        cmd.insert(-1, "--host-store")
    return cmd


def test_two_nodes_crash_restart(tmp_path):
    """2 agents x 2 workers; rank 3 (on node B) crashes; both agents restart
    their workers via a new round and the job completes."""
    port = free_port()
    env = base_env(tmp_path)
    env["TOY_FAIL"] = "0:3:4"
    a = subprocess.Popen(
        launcher_cmd(port, "2", "nodeA", host_store=True, nproc=2),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    b = subprocess.Popen(
        launcher_cmd(port, "2", "nodeB", nproc=2),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    out_a, _ = a.communicate(timeout=120)
    out_b, _ = b.communicate(timeout=120)
    if a.returncode != 0 or b.returncode != 0:
        print("A:", out_a[-3000:])
        print("B:", out_b[-3000:])
    assert a.returncode == 0
    assert b.returncode == 0
    assert int((tmp_path / "progress.txt").read_text()) == 12
    combined = out_a + out_b
    assert "injecting crash" in combined
    assert "cycle=1 starting at iter" in combined


def test_hot_spare_takes_over(tmp_path):
    """3 agents, nnodes 2:2 -> one standby spare. A participant node's worker
    crashes with restarts exhausted for that node? No — simpler and sharper:
    a participant is marked unhealthy at cycle>=1 via the injected node
    failure gate, so on restart the spare replaces it and the job finishes."""
    port = free_port()
    env = base_env(tmp_path, iters=10)
    env["TOY_FAIL"] = "0:1:3"  # crash rank 1 in cycle 0 -> forces round 2
    # nodeB becomes unhealthy from cycle 1 on: the spare must take its place
    env["TPURX_INJECT_NODE_FAILURE"] = "1:nodeB"
    procs = {}
    procs["A"] = subprocess.Popen(
        launcher_cmd(port, "2:2", "nodeA", host_store=True),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    time.sleep(0.5)
    procs["B"] = subprocess.Popen(
        launcher_cmd(port, "2:2", "nodeB"),
        cwd=str(REPO), env=dict(env), stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    procs["C"] = subprocess.Popen(
        launcher_cmd(port, "2:2", "nodeC"),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    outs = {}
    for name, p in procs.items():
        try:
            outs[name], _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            p.kill()
            outs[name], _ = p.communicate()
    if procs["A"].returncode != 0 or procs["C"].returncode != 0:
        for name in outs:
            print(f"=== {name} ===\n", outs[name][-3000:])
    # A (host) and C (spare-then-participant) finish the job
    assert procs["A"].returncode == 0
    assert procs["C"].returncode == 0
    assert int((tmp_path / "progress.txt").read_text()) == 10
    assert "injecting crash" in outs["A"] + outs["B"] + outs["C"]


def test_two_nodes_crash_restart_native_store(tmp_path):
    """Same two-node crash/restart flow, served by the C++ store."""
    port = free_port()
    env = base_env(tmp_path)
    env["TOY_FAIL"] = "0:3:4"
    env["TPURX_NATIVE_STORE"] = "1"
    a = subprocess.Popen(
        launcher_cmd(port, "2", "nodeA", host_store=True, nproc=2),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    b = subprocess.Popen(
        launcher_cmd(port, "2", "nodeB", nproc=2),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    out_a, _ = a.communicate(timeout=120)
    out_b, _ = b.communicate(timeout=120)
    if a.returncode != 0 or b.returncode != 0:
        print("A:", out_a[-3000:])
        print("B:", out_b[-3000:])
    assert a.returncode == 0 and b.returncode == 0
    assert int((tmp_path / "progress.txt").read_text()) == 12
    assert "hosting native C++ store" in out_a


def test_monitor_health_failure_excludes_node_midcycle(tmp_path):
    """A node's rank-monitor health loop trips mid-cycle (injected kernel-log
    fault); the launcher excludes the node WITHOUT waiting for a worker
    failure or the pre-join gate, a spare takes its place, and the job
    completes.  Reference: watchdog-hosted health loops feeding node
    exclusion (``rank_monitor_server.py:122``)."""
    port = free_port()
    iters = 60
    env = base_env(tmp_path, iters=iters)
    env["TOY_STEP_TIME"] = "0.1"  # ~6s cycle: room to trip health mid-cycle
    klog = tmp_path / "nodeB_kern.log"
    klog.write_text("")
    env_b = dict(env)
    env_b.update(
        {
            "TPURX_FT_MONITOR_HEALTH_CHECK_INTERVAL": "0.2",
            "TPURX_FT_MONITOR_HEALTH_CHECKS": "kernel_log",
            "TPURX_FT_MONITOR_HEALTH_KERNEL_LOG": str(klog),
        }
    )
    procs = {}
    procs["A"] = subprocess.Popen(
        launcher_cmd(port, "2:2", "nodeA", host_store=True),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    time.sleep(0.5)
    procs["B"] = subprocess.Popen(
        launcher_cmd(port, "2:2", "nodeB"),
        cwd=str(REPO), env=env_b, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    # B must join before C so B is a participant and C the hot spare
    time.sleep(1.0)
    procs["C"] = subprocess.Popen(
        launcher_cmd(port, "2:2", "nodeC"),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    # Inject the hardware fault only once cycle 0 is provably running (the
    # kernel-log check correctly baselines past anything written before the
    # monitor started — injecting earlier would be silently ignored).
    prog = tmp_path / "progress.txt"
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        try:
            if int(prog.read_text() or "0") >= 5:
                break
        except (OSError, ValueError):
            pass
        time.sleep(0.2)
    else:
        raise AssertionError("cycle 0 never made progress")
    with open(klog, "a") as f:
        f.write("accel accel0: fatal hardware fault, chip reset\n")
    outs = {}
    for name, p in procs.items():
        try:
            outs[name], _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            p.kill()
            outs[name], _ = p.communicate()
    if procs["A"].returncode != 0 or procs["C"].returncode != 0:
        for name in outs:
            print(f"=== {name} ===\n", outs[name][-4000:])
    assert procs["A"].returncode == 0
    assert procs["C"].returncode == 0
    assert "excluding this node" in outs["B"]
    assert int((tmp_path / "progress.txt").read_text()) == iters
