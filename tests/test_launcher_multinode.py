"""Multi-launcher integration: several agents (=nodes) over one store.

Reference analog: multi-agent func tests with hot spares
(``ft_rendezvous_barrier.py:1842-1865`` standby path).
"""

import http.server
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
TOY = str(REPO / "tests" / "workloads" / "toy_train.py")


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def base_env(tmp_path, iters=12):
    env = dict(os.environ)
    env.update(
        {
            "TPURX_REPO": str(REPO),
            "TOY_ITERS": str(iters),
            "TOY_CKPT": str(tmp_path / "progress.txt"),
            "TPURX_FT_ENABLE_DEVICE_HEALTH_CHECK": "0",
            "TPURX_FT_WORKLOAD_CHECK_INTERVAL": "0.1",
            "TPURX_FT_WORKERS_STOP_TIMEOUT": "3.0",
            "TPURX_FT_RDZV_ROUND_TIMEOUT": "30.0",
        }
    )
    return env


def launcher_cmd(port, nnodes, node_id, host_store=False, nproc=1, max_restarts=3):
    cmd = [
        sys.executable, "-m", "tpu_resiliency.fault_tolerance.launcher",
        "--nnodes", nnodes, "--nproc-per-node", str(nproc),
        "--rdzv-endpoint", f"127.0.0.1:{port}",
        "--node-id", node_id,
        "--max-restarts", str(max_restarts),
        "--monitor-interval", "0.05",
        TOY,
    ]
    if host_store:
        cmd.insert(-1, "--host-store")
    return cmd


def test_two_nodes_crash_restart(tmp_path):
    """2 agents x 2 workers; rank 3 (on node B) crashes; both agents restart
    their workers via a new round and the job completes."""
    port = free_port()
    env = base_env(tmp_path)
    env["TOY_FAIL"] = "0:3:4"
    a = subprocess.Popen(
        launcher_cmd(port, "2", "nodeA", host_store=True, nproc=2),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    b = subprocess.Popen(
        launcher_cmd(port, "2", "nodeB", nproc=2),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    out_a, _ = a.communicate(timeout=120)
    out_b, _ = b.communicate(timeout=120)
    if a.returncode != 0 or b.returncode != 0:
        print("A:", out_a[-3000:])
        print("B:", out_b[-3000:])
    assert a.returncode == 0
    assert b.returncode == 0
    assert int((tmp_path / "progress.txt").read_text()) == 12
    combined = out_a + out_b
    assert "injecting crash" in combined
    assert "cycle=1 starting at iter" in combined


def test_hot_spare_takes_over(tmp_path):
    """3 agents, nnodes 2:2 -> one standby spare. A participant node's worker
    crashes with restarts exhausted for that node? No — simpler and sharper:
    a participant is marked unhealthy at cycle>=1 via the injected node
    failure gate, so on restart the spare replaces it and the job finishes."""
    port = free_port()
    env = base_env(tmp_path, iters=10)
    env["TOY_FAIL"] = "0:1:3"  # crash rank 1 in cycle 0 -> forces round 2
    # nodeB becomes unhealthy from cycle 1 on: the spare must take its place
    env["TPURX_INJECT_NODE_FAILURE"] = "1:nodeB"
    procs = {}
    procs["A"] = subprocess.Popen(
        launcher_cmd(port, "2:2", "nodeA", host_store=True),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    time.sleep(0.5)
    procs["B"] = subprocess.Popen(
        launcher_cmd(port, "2:2", "nodeB"),
        cwd=str(REPO), env=dict(env), stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    procs["C"] = subprocess.Popen(
        launcher_cmd(port, "2:2", "nodeC"),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    outs = {}
    for name, p in procs.items():
        try:
            outs[name], _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            p.kill()
            outs[name], _ = p.communicate()
    if procs["A"].returncode != 0 or procs["C"].returncode != 0:
        for name in outs:
            print(f"=== {name} ===\n", outs[name][-3000:])
    # A (host) and C (spare-then-participant) finish the job
    assert procs["A"].returncode == 0
    assert procs["C"].returncode == 0
    assert int((tmp_path / "progress.txt").read_text()) == 10
    assert "injecting crash" in outs["A"] + outs["B"] + outs["C"]


def test_two_nodes_crash_restart_native_store(tmp_path):
    """Same two-node crash/restart flow, served by the C++ store."""
    port = free_port()
    env = base_env(tmp_path)
    env["TOY_FAIL"] = "0:3:4"
    env["TPURX_NATIVE_STORE"] = "1"
    a = subprocess.Popen(
        launcher_cmd(port, "2", "nodeA", host_store=True, nproc=2),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    b = subprocess.Popen(
        launcher_cmd(port, "2", "nodeB", nproc=2),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    out_a, _ = a.communicate(timeout=120)
    out_b, _ = b.communicate(timeout=120)
    if a.returncode != 0 or b.returncode != 0:
        print("A:", out_a[-3000:])
        print("B:", out_b[-3000:])
    assert a.returncode == 0 and b.returncode == 0
    assert int((tmp_path / "progress.txt").read_text()) == 12
    assert "hosting native C++ store" in out_a


def test_heterogeneous_worker_counts_across_two_agents(tmp_path):
    """Two agents with DIFFERENT worker counts under --allow-heterogeneous
    (VERDICT r5 weak #5): A contributes 2 slots, B contributes 1, the
    rendezvous accepts the mixed fleet and assigns a contiguous 3-rank
    world, and the job completes on all three ranks."""
    port = free_port()
    env = base_env(tmp_path)

    def hetero(cmd):
        cmd = list(cmd)
        cmd.insert(-1, "--allow-heterogeneous")  # before the workload arg
        return cmd

    a = subprocess.Popen(
        hetero(launcher_cmd(port, "2", "nodeA", host_store=True, nproc=2)),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    b = subprocess.Popen(
        hetero(launcher_cmd(port, "2", "nodeB", nproc=1)),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    out_a, _ = a.communicate(timeout=120)
    out_b, _ = b.communicate(timeout=120)
    if a.returncode != 0 or b.returncode != 0:
        print("A:", out_a[-3000:])
        print("B:", out_b[-3000:])
    assert a.returncode == 0 and b.returncode == 0
    combined = out_a + out_b
    # a 3-rank world formed from 2+1 slots, every rank ran to completion
    for rank in range(3):
        assert f"toy[{rank}/3]" in combined
    assert combined.count("] done (12 iters)") == 3
    assert int((tmp_path / "progress.txt").read_text()) == 12


def test_heterogeneous_worker_counts_rejected_without_flag(tmp_path):
    """The same 2+1 fleet WITHOUT the flag must refuse to form (equal-slot
    invariant), not silently build a lopsided world."""
    port = free_port()
    env = base_env(tmp_path, iters=6)
    env["TPURX_FT_RDZV_ROUND_TIMEOUT"] = "15.0"
    a = subprocess.Popen(
        launcher_cmd(port, "2", "nodeA", host_store=True, nproc=2,
                     max_restarts=0),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    b = subprocess.Popen(
        launcher_cmd(port, "2", "nodeB", nproc=1, max_restarts=0),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    try:
        out_a, _ = a.communicate(timeout=90)
        out_b, _ = b.communicate(timeout=90)
    except subprocess.TimeoutExpired:
        a.kill(); b.kill()
        out_a, _ = a.communicate()
        out_b, _ = b.communicate()
    combined = out_a + out_b
    assert "heterogeneous slots per node" in combined
    assert "done (6 iters)" not in combined


def test_spare_promotion_races_store_host_sigkill(tmp_path):
    """Spare promotion WHILE the store host dies (VERDICT r5 ask #8,
    composing the hot-spare and store-outage tests): the control plane runs
    externally with a journal; mid-job — right as a worker crash forces the
    promotion round — the store is SIGKILLed and restarted.  The agents
    must ride out the outage, the spare must still replace the unhealthy
    participant, and the job must finish."""
    port = free_port()
    journal = tmp_path / "store.journal"
    env = base_env(tmp_path, iters=10)
    env["TOY_STEP_TIME"] = "0.2"          # slow steps: a real race window
    env["TOY_FAIL"] = "0:1:3"             # crash rank 1 -> promotion round
    env["TPURX_INJECT_NODE_FAILURE"] = "1:nodeB"
    env["TPURX_FT_STORE_REJOIN_WINDOW"] = "120.0"

    def spawn_store():
        return subprocess.Popen(
            [sys.executable, "-m",
             "tpu_resiliency.fault_tolerance.control_plane",
             "--host", "127.0.0.1", "--port", str(port),
             "--journal", str(journal)],
            cwd=str(REPO), env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )

    store = spawn_store()
    time.sleep(1.5)  # let it bind before the agents dial
    procs = {}
    try:
        procs["A"] = subprocess.Popen(
            launcher_cmd(port, "2:2", "nodeA"),
            cwd=str(REPO), env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        time.sleep(0.5)
        procs["B"] = subprocess.Popen(
            launcher_cmd(port, "2:2", "nodeB"),
            cwd=str(REPO), env=dict(env), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        time.sleep(1.0)  # B joins before C -> C is the hot spare
        procs["C"] = subprocess.Popen(
            launcher_cmd(port, "2:2", "nodeC"),
            cwd=str(REPO), env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        # kill the store the moment the crash iteration is imminent, so the
        # outage overlaps the failure detection + promotion rendezvous
        prog = tmp_path / "progress.txt"
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                if int(prog.read_text() or "0") >= 2:
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.1)
        else:
            raise AssertionError("cycle 0 never made progress")
        os.kill(store.pid, signal.SIGKILL)
        store.wait(timeout=10)
        time.sleep(2.0)                   # outage window
        store = spawn_store()             # journal replays prior state
        outs = {}
        for name, p in procs.items():
            try:
                outs[name], _ = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                p.kill()
                outs[name], _ = p.communicate()
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        store.terminate()
        try:
            store.wait(timeout=10)
        except subprocess.TimeoutExpired:
            store.kill()
    if procs["A"].returncode != 0 or procs["C"].returncode != 0:
        for name in outs:
            print(f"=== {name} ===\n", outs[name][-4000:])
    # A and the promoted spare C finish despite the store-host SIGKILL
    assert procs["A"].returncode == 0
    assert procs["C"].returncode == 0
    assert int((tmp_path / "progress.txt").read_text()) == 10
    assert "injecting crash" in outs["A"] + outs["B"] + outs["C"]


class _DenyAttrSvc(http.server.BaseHTTPRequestHandler):
    """Fake attribution service: every verdict is a confident deny."""

    def _reply(self, obj):
        body = json.dumps(obj).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        self._reply({"ok": True})

    def do_POST(self):
        self.rfile.read(int(self.headers.get("Content-Length", "0")))
        self._reply({
            "category": "oom",
            "should_resume": False,
            "confidence": 0.95,
            "summary": "device OOM: restart cannot succeed at this batch size",
        })

    def log_message(self, *args):  # quiet
        pass


def test_attribution_deny_stops_launcher_without_restart(tmp_path):
    """Attribution-deny through the REAL launcher (VERDICT r5 ask #8): a
    fake attrsvc returns should_resume=false at confidence 0.95, so after
    the worker's crash the gate refuses the restart — no cycle 1, the
    launcher stops and reports the failure instead of burning restarts on
    an unsurvivable fault."""
    svc = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _DenyAttrSvc)
    svc_port = svc.server_address[1]
    t = threading.Thread(target=svc.serve_forever, daemon=True)
    t.start()
    try:
        port = free_port()
        env = base_env(tmp_path, iters=12)
        env["TOY_FAIL"] = "0:0:3"
        env["TOY_FAIL_MSG"] = "RESOURCE_EXHAUSTED: out of memory"
        log_dir = tmp_path / "cycle_logs"
        cmd = [
            sys.executable, "-m", "tpu_resiliency.fault_tolerance.launcher",
            "--nnodes", "1", "--nproc-per-node", "1",
            "--rdzv-endpoint", f"127.0.0.1:{port}",
            "--node-id", "nodeA", "--host-store",
            "--max-restarts", "3", "--monitor-interval", "0.05",
            "--log-dir", str(log_dir),
            "--ft-param", "enable_attribution_gate=true",
            "--ft-param", "attribution_service_mode=external",
            "--ft-param",
            f"attribution_service_url=http://127.0.0.1:{svc_port}",
            TOY,
        ]
        proc = subprocess.run(
            cmd, cwd=str(REPO), env=env, capture_output=True, text=True,
            timeout=120,
        )
    finally:
        svc.shutdown()
        svc.server_close()
    blob = proc.stdout + proc.stderr
    # the gate consulted the service and refused the restart
    assert "attribution (service)" in blob
    assert "not survivable by restart" in blob
    # no cycle 1 ever started; the job stopped with a failure
    assert "cycle=1 starting" not in proc.stdout
    assert proc.returncode != 0


def test_monitor_health_failure_excludes_node_midcycle(tmp_path):
    """A node's rank-monitor health loop trips mid-cycle (injected kernel-log
    fault); the launcher excludes the node WITHOUT waiting for a worker
    failure or the pre-join gate, a spare takes its place, and the job
    completes.  Reference: watchdog-hosted health loops feeding node
    exclusion (``rank_monitor_server.py:122``)."""
    port = free_port()
    iters = 60
    env = base_env(tmp_path, iters=iters)
    env["TOY_STEP_TIME"] = "0.1"  # ~6s cycle: room to trip health mid-cycle
    klog = tmp_path / "nodeB_kern.log"
    klog.write_text("")
    env_b = dict(env)
    env_b.update(
        {
            "TPURX_FT_MONITOR_HEALTH_CHECK_INTERVAL": "0.2",
            "TPURX_FT_MONITOR_HEALTH_CHECKS": "kernel_log",
            "TPURX_FT_MONITOR_HEALTH_KERNEL_LOG": str(klog),
        }
    )
    procs = {}
    procs["A"] = subprocess.Popen(
        launcher_cmd(port, "2:2", "nodeA", host_store=True),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    time.sleep(0.5)
    procs["B"] = subprocess.Popen(
        launcher_cmd(port, "2:2", "nodeB"),
        cwd=str(REPO), env=env_b, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    # B must join before C so B is a participant and C the hot spare
    time.sleep(1.0)
    procs["C"] = subprocess.Popen(
        launcher_cmd(port, "2:2", "nodeC"),
        cwd=str(REPO), env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    # Inject the hardware fault only once cycle 0 is provably running (the
    # kernel-log check correctly baselines past anything written before the
    # monitor started — injecting earlier would be silently ignored).
    prog = tmp_path / "progress.txt"
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        try:
            if int(prog.read_text() or "0") >= 5:
                break
        except (OSError, ValueError):
            pass
        time.sleep(0.2)
    else:
        raise AssertionError("cycle 0 never made progress")
    with open(klog, "a") as f:
        f.write("accel accel0: fatal hardware fault, chip reset\n")
    outs = {}
    for name, p in procs.items():
        try:
            outs[name], _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            p.kill()
            outs[name], _ = p.communicate()
    if procs["A"].returncode != 0 or procs["C"].returncode != 0:
        for name in outs:
            print(f"=== {name} ===\n", outs[name][-4000:])
    assert procs["A"].returncode == 0
    assert procs["C"].returncode == 0
    assert "excluding this node" in outs["B"]
    assert int((tmp_path / "progress.txt").read_text()) == iters
