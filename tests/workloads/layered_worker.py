"""Layered-restart workload: in-process Wrapper UNDER the elastic launcher.

The key composition (SURVEY.md §1): the wrapper recovers faults in-process
while the launcher's rank monitor knows (via the nested-restarter section)
that recovery is in progress; only faults the wrapper cannot survive fall
through to the launcher ring.

Scenario (env LAYERED_SCENARIO):
  inner  — rank 1 raises at wrapper-iteration 0; the in-process ring recovers
           it; the LAUNCHER must see zero worker failures (cycle stays 0).
           With TPURX_SHRINK_MESH=1 the abort ladder's ShrinkMeshStage runs
           on the recovery path (no distributed client here, so it releases
           by clearing caches+backends) — the opt-in rung end to end.
  outer  — rank 1 hard-exits; the in-process ring cannot save a dead process;
           its launcher respawns it and the wrapper group re-forms.
  stall  — the wedged-COLLECTIVE case the abort ladder absorbs in-process:
           both ranks record a dispatch of ``unified_allreduce`` every step
           (the at-abort fingerprint feed); rank 1 stops beating mid-run (a
           ping-less wait, how a rank parked on a missing participant
           presents when the interpreter still runs).  The armed quorum
           tripwire records QUORUM_STALE, every rank's ladder publishes its
           dispatch tail, the trace-analyzer verdict names the in-flight op
           and the lagging rank, and the ring restarts in-process — the
           launcher never sees a failure.
  degrade — the link fault the SELF-HEALING COLLECTIVE layer absorbs below
           both restart rings (docs/collectives.md): every step runs a
           wrapped collective (``device_max_reduce``); the armed rank
           (``TPURX_FAULT=coll_stall``) has its primary lane stall past the
           deadline every call, so the wrapper walks retry → re-layout in
           process and the route-health bias keeps later calls off the dead
           primary.  Mid-run the armed rank also trips a shrink-only probe
           through the Wrapper-installed DegradeToShrink hook, running the
           real (opt-in) ShrinkMeshStage as a TARGETED rung.  Neither the
           in-process ring nor the launcher ever sees a fault: zero wrapper
           restarts, zero launcher cycles.
  wedged — rank 1 blocks forever inside a DEVICE program (a jit'd infinite
           while_loop: stuck in PJRT C++ with the GIL released — how a
           collective with a missing participant presents to Python).  The
           async raise cannot land, pings and the watchdog's pending-call
           auto-stamps freeze, so the exec'd monitor process records
           SOFT_TIMEOUT (folding in the rank's dispatch tail read from shm
           post-mortem) and then hard-kills at the hard timeout; the
           launcher ring re-rendezvouses.  Reference layered contract:
           ``inprocess/monitor_process.py:269-288`` (GIL-released hang ->
           kill) + ``inprocess/nested_restarter.py:36-107``.
"""

import os
import sys
import time

sys.path.insert(0, os.environ.get("TPURX_REPO", "/root/repo"))

from tpu_resiliency.fault_tolerance import FaultToleranceConfig, RankMonitorClient
from tpu_resiliency.fault_tolerance.progress_tracker import write_progress_iteration
from tpu_resiliency.inprocess import ShiftRanks, Wrapper, record_dispatch
from tpu_resiliency.inprocess.nested_restarter import NestedRestarterCallback

RANK = int(os.environ["TPURX_RANK"])
CYCLE = int(os.environ["TPURX_CYCLE"])
SCENARIO = os.environ.get("LAYERED_SCENARIO", "inner")
# inner/stall recover IN-PROCESS: the healthy rank must not be able to
# complete the whole fn before the trip -> abort ladder -> restart raise
# lands on a loaded host (completion would legitimately end the job at
# iteration 0).  wedged/outer DEPEND on the short run: rank 0 finishing
# cycle 0 quickly is part of those scenarios' choreography.
STEPS = int(os.environ.get("LAYERED_STEPS")
            or (120 if SCENARIO in ("inner", "stall") else 40))

quorum_kw = {}
if SCENARIO == "stall":
    # the stall is detected by the on-device quorum tripwire (manual beats:
    # ping() IS the progress signal, so a ping-less rank reads as stale)
    import jax
    import numpy as np

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from jax.sharding import Mesh

    quorum_kw = dict(
        quorum_mesh=Mesh(np.array(jax.devices()), ("d",)),
        quorum_budget_ms=500.0,
        quorum_interval=0.05,
        quorum_auto_beat_interval=None,
        quorum_calibrate=False,
    )

client = RankMonitorClient(
    FaultToleranceConfig(
        rank_section_timeouts={"inprocess_restart": 30.0},
        skip_section_response=False,
    )
)
client.init_workload_monitoring()
bridge = NestedRestarterCallback(client)


@Wrapper(
    group=f"layered-c{CYCLE}",
    rank_assignment=ShiftRanks(),
    initialize=bridge.on_initialize,
    abort=bridge.on_abort,
    finalize=bridge.on_finalize,
    soft_timeout=float(os.environ.get("WRAP_SOFT_TIMEOUT", "15.0")),
    hard_timeout=float(os.environ.get("WRAP_HARD_TIMEOUT", "30.0")),
    monitor_process_interval=0.2,
    monitor_thread_interval=0.1,
    heartbeat_interval=0.2,
    sibling_timeout=3.0,
    **quorum_kw,
)
def train(call_wrapper=None):
    it = call_wrapper.iteration
    state = call_wrapper.state
    print(f"train rank={state.active_rank} world={state.active_world_size} "
          f"iter={it} cycle={CYCLE}", flush=True)
    for step in range(STEPS):
        call_wrapper.ping()
        client.send_heartbeat()
        # at-abort fingerprint feed: the step's collective, at dispatch
        record_dispatch("unified_allreduce")
        time.sleep(0.05)
        if SCENARIO == "degrade":
            from tpu_resiliency.parallel import device_max_reduce

            # the step collective, wrapped: the armed rank's primary lane
            # stalls past deadline and the ladder absorbs it IN PROCESS
            got = device_max_reduce([float(step)])
            assert got and got[0] >= float(step), got
            if RANK == 1 and step == 3:
                # targeted-shrink probe: a shrink-only ladder walks the
                # Wrapper-installed DegradeToShrink hook — the real
                # ShrinkMeshStage (TPURX_SHRINK_MESH=1) as ONE rung, not a
                # restart; the healthy fallback lane completes the op
                from tpu_resiliency.parallel import ResilientCollective
                from tpu_resiliency.parallel.degrade import DegradePolicy

                probe = ResilientCollective(
                    "shrink_probe", lambda: "primary", axis="ici",
                    fallback=lambda: "shrunk", deadline_ms=250.0,
                    policy=DegradePolicy(rungs=("shrink",), retries=0),
                )
                print(f"shrink probe -> {probe()}", flush=True)
        if CYCLE == 0 and it == 0 and RANK == 1 and step == 5:
            if SCENARIO == "inner":
                raise RuntimeError("inner fault: recover in-process")
            if SCENARIO == "outer":
                print("outer fault: dying for real", flush=True)
                os._exit(29)
            if SCENARIO == "stall":
                print("stalling: parked on a collective, no beats", flush=True)
                # a ping-less wait: the interpreter still runs (the restart
                # raise can land) but progress beats stop — the quorum
                # tripwire must name this rank from the pod-wide age reduce
                while True:
                    time.sleep(0.02)
            if SCENARIO == "wedged":
                print("wedging in a device program", flush=True)
                import jax
                import jax.numpy as jnp

                if os.environ.get("JAX_PLATFORMS") == "cpu":
                    # sitecustomize force-selects the TPU platform through
                    # jax.config, overriding the env var — override it back
                    jax.config.update("jax_platforms", "cpu")
                spin = jax.jit(
                    lambda x: jax.lax.while_loop(
                        lambda c: jnp.bool_(True), lambda c: c + 1, x
                    )
                )
                # the dispatch lands in the shm tail BEFORE the block: the
                # monitor process reads it post-mortem for the fingerprint
                record_dispatch("spin_forever")
                # never returns: the main thread is blocked inside the PJRT
                # runtime with the GIL released — pings and pending-call
                # stamps freeze, async raises cannot land
                spin(jnp.int32(0)).block_until_ready()
        if state.active_rank == 0:
            write_progress_iteration(os.environ["TOY_CKPT"], step)
    if SCENARIO == "degrade":
        from tpu_resiliency.telemetry import get_registry

        def metric_sum(name):
            m = get_registry().get(name)
            if m is None:
                return 0.0
            return sum(v.get("value", 0.0) for _l, v in m._sample_rows())

        print(
            f"colldeg[{RANK}] "
            f"degrades={int(metric_sum('tpurx_collective_degrades_total'))} "
            f"timeouts={int(metric_sum('tpurx_collective_timeouts_total'))}",
            flush=True,
        )
    return f"done@{it}"


if __name__ == "__main__":
    ret = train()
    print(f"RESULT rank={RANK} cycle={CYCLE} ret={ret}", flush=True)
