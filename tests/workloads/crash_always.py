"""Workload that always crashes immediately (restart-budget tests)."""

import os
import sys

sys.path.insert(0, os.environ.get("TPURX_REPO", "/root/repo"))

print(f"crash_always: cycle={os.environ.get('TPURX_CYCLE')}", flush=True)
os._exit(23)
