"""Toy training workload for launcher integration tests.

Mirrors the reference's func-test DDP toys
(``tests/fault_tolerance/func/run_local_ddp_test_heartbeats.sh`` workloads):
iterate, heartbeat to the rank monitor, persist progress, optionally inject a
crash or a hang at a given (cycle, rank, iteration).

Env:
  TOY_ITERS       total iterations (default 20)
  TOY_CKPT        progress file path ("checkpoint")
  TOY_FAIL        "cycle:rank:iter" -> crash with rc 17
  TOY_HANG        "cycle:rank:iter" -> stop heartbeating forever
  TOY_QUORUM_HANG "cycle:rank:iter" -> stop quorum-beating (stall) with the
                  on-device quorum tripwire wired to request an in-job
                  restart (WorkloadControlRequest.RestartWorkload)
  TOY_STEP_TIME   seconds per iteration (default 0.05)
"""

import os
import sys
import time

sys.path.insert(0, os.environ.get("TPURX_REPO", "/root/repo"))

from tpu_resiliency.fault_tolerance import FaultToleranceConfig, RankMonitorClient
from tpu_resiliency.fault_tolerance.progress_tracker import write_progress_iteration


def parse_spec(name):
    spec = os.environ.get(name)
    if not spec:
        return None
    return tuple(int(x) for x in spec.split(":"))


def main():
    rank = int(os.environ["TPURX_RANK"])
    cycle = int(os.environ["TPURX_CYCLE"])
    world = int(os.environ["TPURX_WORLD_SIZE"])
    total = int(os.environ.get("TOY_ITERS", "20"))
    step_time = float(os.environ.get("TOY_STEP_TIME", "0.05"))
    ckpt = os.environ.get("TOY_CKPT")
    fail = parse_spec("TOY_FAIL")
    hang = parse_spec("TOY_HANG")
    quorum_hang = parse_spec("TOY_QUORUM_HANG")

    start = 0
    if ckpt and os.path.exists(ckpt):
        with open(ckpt) as f:
            start = int(f.read().strip() or "0")

    client = RankMonitorClient()
    client.init_workload_monitoring()

    quorum = None
    if quorum_hang:
        import jax

        if os.environ.get("JAX_PLATFORMS") == "cpu":
            jax.config.update("jax_platforms", "cpu")  # undo axon override
        import numpy as np
        from jax.sharding import Mesh

        from tpu_resiliency.inprocess import quorum_restart_requester
        from tpu_resiliency.ops import QuorumMonitor

        quorum = QuorumMonitor(
            Mesh(np.array(jax.devices()), ("d",)),
            budget_ms=float(os.environ.get("TOY_QUORUM_BUDGET_MS", "500")),
            interval=0.02,
            auto_beat_interval=None,  # manual beats: progress semantics
            on_stale=quorum_restart_requester(client),
            identify=True,
        )
        quorum.start()

    print(f"toy[{rank}/{world}] cycle={cycle} starting at iter {start}", flush=True)

    for it in range(start, total):
        client.send_heartbeat()
        if quorum is not None:
            quorum.beat()
        time.sleep(step_time)
        if fail and (cycle, rank, it) == fail:
            fail_msg = os.environ.get("TOY_FAIL_MSG")
            if fail_msg:
                print(fail_msg, flush=True)  # e.g. an OOM signature for the gate
            print(f"toy[{rank}] injecting crash at iter {it}", flush=True)
            os._exit(17)
        if hang and (cycle, rank, it) == hang:
            print(f"toy[{rank}] injecting hang at iter {it}", flush=True)
            time.sleep(3600)
        if quorum_hang and (cycle, rank, it) == quorum_hang:
            # keep heartbeating the HOST monitor (its timeout is huge in the
            # test) but stall the quorum beats: only the on-device tripwire
            # can name this hang and request the cycle restart
            print(f"toy[{rank}] injecting quorum-stall at iter {it}", flush=True)
            while True:
                client.send_heartbeat()
                time.sleep(0.1)
        if rank == 0 and ckpt:
            write_progress_iteration(ckpt, it + 1)
    if quorum is not None:
        quorum.stop()
    print(f"toy[{rank}] done ({total} iters)", flush=True)


if __name__ == "__main__":
    main()
