"""Worker for in-process restart tests (reference analog: tests/inprocess/app.py).

Env:
  TPURX_RANK / TPURX_WORLD_SIZE   identity
  TPURX_STORE_ADDR / PORT         store
  SCENARIO                        clean | exception | crash | hang | spare
  FAIL_RANK                       rank that faults (default 1)
  STEPS                           steps per fn run (default 30)
Prints "RESULT rank=<r> iters=<n> world=<w> ret=<ret>" on success.
"""

import os
import sys
import time

sys.path.insert(0, os.environ.get("TPURX_REPO", "/root/repo"))

from tpu_resiliency.inprocess import (
    Compose,
    MaxActiveWorldSize,
    ShiftRanks,
    Wrapper,
)

SCENARIO = os.environ.get("SCENARIO", "clean")
FAIL_RANK = int(os.environ.get("FAIL_RANK", "1"))
STEPS = int(os.environ.get("STEPS", "60"))
INITIAL_RANK = int(os.environ["TPURX_RANK"])

calls = {"n": 0}


def train(call_wrapper=None):
    calls["n"] += 1
    it = call_wrapper.iteration
    state = call_wrapper.state
    rank = state.active_rank
    world = state.active_world_size
    print(
        f"train start rank={rank} world={world} iter={it} call={calls['n']}",
        flush=True,
    )
    for step in range(STEPS):
        call_wrapper.ping()
        time.sleep(0.05)
        if it == 0 and INITIAL_RANK == FAIL_RANK and step == 3:
            if "exception" in SCENARIO:
                raise RuntimeError("injected exception")
            if "crash" in SCENARIO:
                print("crashing", flush=True)
                os._exit(31)
            if "hang" in SCENARIO:
                print("hanging", flush=True)
                time.sleep(3600)  # stops pinging; GIL released
    return f"ok@{it}"


def main():
    assignment = (
        Compose(ShiftRanks(), MaxActiveWorldSize(int(os.environ.get("MAX_ACTIVE", "2"))))
        if SCENARIO.startswith("spare")
        else ShiftRanks()
    )
    wrapper = Wrapper(
        rank_assignment=assignment,
        soft_timeout=float(os.environ.get("SOFT_TIMEOUT", "1.0")),
        hard_timeout=float(os.environ.get("HARD_TIMEOUT", "2.5")),
        monitor_process_interval=0.2,
        monitor_thread_interval=0.1,
        last_call_wait=0.2,
        heartbeat_interval=0.2,
        sibling_timeout=2.0,
        barrier_timeout=30.0,
    )
    wrapped = wrapper(train)
    ret = wrapped()
    final_rank = os.environ.get("TPURX_RANK")
    print(
        f"RESULT rank={INITIAL_RANK} calls={calls['n']} "
        f"final_rank={final_rank} ret={ret}",
        flush=True,
    )


if __name__ == "__main__":
    main()
