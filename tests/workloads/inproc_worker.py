"""Worker for in-process restart tests (reference analog: tests/inprocess/app.py).

Env:
  TPURX_RANK / TPURX_WORLD_SIZE   identity
  TPURX_STORE_ADDR / PORT         store
  SCENARIO                        clean | exception | crash | hang | spare
                                  | tree_crash | tree_hostcrash
  FAIL_RANK                       rank that faults (default 1)
  STEPS                           steps per fn run (default 30)
Prints "RESULT rank=<r> iters=<n> world=<w> ret=<ret>" on success.
"""

import os
import sys
import time

sys.path.insert(0, os.environ.get("TPURX_REPO", "/root/repo"))

from tpu_resiliency.inprocess import (
    Compose,
    Layer,
    LayerFlag,
    MaxActiveWorldSize,
    RankDiscontinued,
    ShiftRanks,
    Tree,
    Wrapper,
)

SCENARIO = os.environ.get("SCENARIO", "clean")
FAIL_RANK = int(os.environ.get("FAIL_RANK", "1"))
STEPS = int(os.environ.get("STEPS", "60"))
INITIAL_RANK = int(os.environ["TPURX_RANK"])

calls = {"n": 0}


def train(call_wrapper=None):
    calls["n"] += 1
    it = call_wrapper.iteration
    state = call_wrapper.state
    rank = state.active_rank
    world = state.active_world_size
    print(
        f"train start rank={rank} world={world} iter={it} call={calls['n']}",
        flush=True,
    )
    for step in range(STEPS):
        call_wrapper.ping()
        time.sleep(0.05)
        if SCENARIO == "late_fault" and it == 0:
            # completion/fault race: rank 0 finishes the job early; the
            # failing rank faults well after — its restart path must see
            # any_completed and EXIT instead of restarting into an
            # iteration barrier the completed rank will never attend
            if INITIAL_RANK == 0 and step == 1:
                return f"done-early@{it}"
            if INITIAL_RANK == FAIL_RANK and step == 30:
                raise RuntimeError("late fault after completion")
        if it == 0 and INITIAL_RANK == FAIL_RANK and step == 3:
            if "exception" in SCENARIO:
                raise RuntimeError("injected exception")
            if "crash" in SCENARIO:
                print("crashing", flush=True)
                os._exit(31)
            if SCENARIO == "quorum_hang":
                # stop beating: the ICI quorum collective must detect the
                # stale stamp and trip the restart ring — the host-side
                # soft/hard/sibling timeouts are set far too large to fire.
                # Python-level stall (not one long C sleep) so the monitor
                # thread's async raise can land and the SAME process recovers.
                print("quorum-hanging", flush=True)
                while True:
                    time.sleep(0.1)
            if "hang" in SCENARIO:
                print("hanging", flush=True)
                time.sleep(3600)  # stops pinging; GIL released
    return f"ok@{it}"


def _tree_assignment():
    """Two-layer pod: root(RESERVE, capped) over N-chip hosts.

    ``tree_crash`` allows partial hosts (spare promotes into a one-chip gap);
    ``tree_hostcrash`` pins min=max=chips so losing one chip terminates the
    whole host and both slots refill from the other host's spares.
    """
    chips = int(os.environ.get("CHIPS_PER_HOST", "2"))
    host_min = 1 if SCENARIO == "tree_crash" else chips
    host_max = 1 if SCENARIO == "tree_crash" else chips
    return Tree(
        [
            Layer(
                min_ranks=1,
                max_ranks=int(os.environ.get("MAX_ACTIVE", "2")),
                key_of_rank="root",
                flag=LayerFlag.RESERVE,
            ),
            Layer(
                min_ranks=host_min,
                max_ranks=host_max,
                key_of_rank=lambda r, c=chips: r // c,
                flag=LayerFlag.RESERVE,
            ),
        ]
    )


def main():
    if SCENARIO.startswith("tree"):
        assignment = _tree_assignment()
    elif SCENARIO.startswith("spare"):
        assignment = Compose(
            ShiftRanks(), MaxActiveWorldSize(int(os.environ.get("MAX_ACTIVE", "2")))
        )
    else:
        assignment = ShiftRanks()
    quorum_kw = {}
    if SCENARIO == "quorum_hang":
        import jax

        if os.environ.get("JAX_PLATFORMS") == "cpu":
            # the axon sitecustomize force-selects the TPU platform through
            # jax.config, overriding the env var — override it back (same
            # dance as tests/conftest.py)
            jax.config.update("jax_platforms", "cpu")
        import numpy as np
        from jax.sharding import Mesh

        quorum_kw = dict(
            quorum_mesh=Mesh(np.array(jax.devices()), ("d",)),
            quorum_budget_ms=float(os.environ.get("QUORUM_BUDGET_MS", "500")),
            quorum_interval=0.02,
            # manual ping() is the only beat source: a stopped training loop
            # means stale stamps (progress semantics, not just liveness)
            quorum_auto_beat_interval=None,
            quorum_calibrate=False,
        )
    wrapper = Wrapper(
        rank_assignment=assignment,
        # defaults sized for loaded CI hosts: scenarios that TEST hang
        # detection override these via env; for everything else a tight
        # budget risks a load-stall being killed as a "hang"
        soft_timeout=float(os.environ.get("SOFT_TIMEOUT", "5.0")),
        hard_timeout=float(os.environ.get("HARD_TIMEOUT", "10.0")),
        monitor_process_interval=0.2,
        monitor_thread_interval=0.1,
        last_call_wait=0.2,
        heartbeat_interval=0.2,
        sibling_timeout=float(os.environ.get("SIBLING_TIMEOUT", "8.0")),
        barrier_timeout=30.0,
        **quorum_kw,
    )
    wrapped = wrapper(train)
    try:
        ret = wrapped()
    except RankDiscontinued as exc:
        # precisely a policy discontinuation (Tree min_ranks propagation),
        # NOT a generic abort — max_iterations/health aborts must fail loud
        print(f"DISCONTINUED rank={INITIAL_RANK} reason={exc}", flush=True)
        sys.exit(7)
    final_rank = os.environ.get("TPURX_RANK")
    print(
        f"RESULT rank={INITIAL_RANK} calls={calls['n']} "
        f"final_rank={final_rank} ret={ret}",
        flush=True,
    )
    if os.environ.get("TPURX_FLIGHT_DIR"):
        # trip-time black boxes end at the detection instant; the soak tests
        # also want the full episode story (decide..resume), so drop one
        # final dump with the complete ring before exiting
        from tpu_resiliency.telemetry import flight

        flight.dump("worker_exit", min_interval_s=0.0)


if __name__ == "__main__":
    main()
