"""In-process restart wrapper tests.

Reference analog: ``tests/inprocess/test_wrap.py`` + ``common.py``'s
MultiProcessTestCase: real OS processes, real store, injected faults.
"""

import os
import re
import subprocess
import sys
import time
from pathlib import Path

import pytest

from tpu_resiliency.utils.env import disarm_platform_sitecustomize

from tpu_resiliency.inprocess.rank_assignment import (
    ActivateAllRanks,
    ActiveWorldSizeDivisibleBy,
    FillGaps,
    MaxActiveWorldSize,
    RankAssignmentCtx,
    RankDiscontinued,
    ShiftRanks,
)
from tpu_resiliency.inprocess.state import Mode, State

REPO = Path(__file__).resolve().parent.parent
WORKER = str(REPO / "tests" / "workloads" / "inproc_worker.py")


# ---- pure policy tests (reference test_rank_assignment.py) -----------------

def _state(rank, world):
    return State(rank=rank, world_size=world)


class TestRankAssignment:
    def test_shift_ranks(self):
        ctx = RankAssignmentCtx(_state(3, 4), {1})
        ShiftRanks()(ctx)
        assert ctx.state.rank == 2
        assert ctx.state.world_size == 3
        assert ctx.state.mode == Mode.ACTIVE

    def test_shift_ranks_discontinued(self):
        with pytest.raises(RankDiscontinued):
            ShiftRanks()(RankAssignmentCtx(_state(1, 4), {1}))

    def test_fill_gaps_keeps_survivors(self):
        # world 4, rank 1 dies: rank 3 moves into slot 1; 0 and 2 unchanged
        ctx = RankAssignmentCtx(_state(2, 4), {1})
        FillGaps()(ctx)
        assert ctx.state.rank == 2
        ctx3 = RankAssignmentCtx(_state(3, 4), {1})
        FillGaps()(ctx3)
        assert ctx3.state.rank == 1
        assert ctx3.state.world_size == 3

    def test_max_active_world_size(self):
        ctx = RankAssignmentCtx(_state(2, 3), set())
        MaxActiveWorldSize(2)(ctx)
        assert ctx.state.mode == Mode.INACTIVE
        assert ctx.state.active_world_size == 2
        ctx0 = RankAssignmentCtx(_state(0, 3), set())
        MaxActiveWorldSize(2)(ctx0)
        assert ctx0.state.mode == Mode.ACTIVE

    def test_divisible_by(self):
        ctx = RankAssignmentCtx(_state(6, 7), set())
        ActiveWorldSizeDivisibleBy(4)(ctx)
        assert ctx.state.active_world_size == 4
        assert ctx.state.mode == Mode.INACTIVE
        ctx2 = RankAssignmentCtx(_state(2, 7), set())
        ActiveWorldSizeDivisibleBy(4)(ctx2)
        assert ctx2.state.mode == Mode.ACTIVE

    def test_activate_all(self):
        ctx = RankAssignmentCtx(_state(1, 2), set())
        ActivateAllRanks()(ctx)
        assert ctx.state.mode == Mode.ACTIVE


# ---- multiprocess wrapper tests --------------------------------------------

def run_scenario(store_server, scenario, world=2, extra_env=None, timeout=90):
    procs = []
    for rank in range(world):
        env = dict(os.environ)
        env.update(
            {
                "TPURX_REPO": str(REPO),
                "TPURX_RANK": str(rank),
                "TPURX_WORLD_SIZE": str(world),
                "TPURX_STORE_ADDR": "127.0.0.1",
                "TPURX_STORE_PORT": str(store_server.port),
                "SCENARIO": scenario,
            }
        )
        disarm_platform_sitecustomize(env)
        env.update(extra_env or {})
        procs.append(
            subprocess.Popen(
                [sys.executable, WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=str(REPO),
            )
        )
    outs = {}
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out += "\n<TIMEOUT>"
        outs[rank] = out
    return procs, outs


def _dump(outs):
    for r, out in outs.items():
        print(f"===== rank {r} =====\n{out[-2500:]}")


def test_clean_run(store_server):
    procs, outs = run_scenario(store_server, "clean", world=2)
    if any(p.returncode != 0 for p in procs):
        _dump(outs)
    for rank, p in enumerate(procs):
        assert p.returncode == 0
        assert "RESULT" in outs[rank]
        assert "ret=ok@0" in outs[rank]
        assert "calls=1" in outs[rank]


def test_exception_restarts_all_ranks(store_server):
    procs, outs = run_scenario(store_server, "exception", world=2)
    if any(p.returncode != 0 for p in procs):
        _dump(outs)
    for rank, p in enumerate(procs):
        assert p.returncode == 0, f"rank {rank}"
        # iteration 0 faulted; completion at >= 1 (extra legitimate restarts
        # possible on a loaded host)
        m = re.search(r"ret=ok@(\d+)", outs[rank])
        assert m and int(m.group(1)) >= 1, outs[rank][-800:]
    assert "injected exception" in outs[1]


def test_crash_shrinks_world(store_server):
    procs, outs = run_scenario(store_server, "crash", world=3, timeout=120)
    if procs[0].returncode != 0 or procs[2].returncode != 0:
        _dump(outs)
    # rank 1 died hard
    assert procs[1].returncode == 31
    # survivors restarted and finished with world 2
    for rank in (0, 2):
        assert procs[rank].returncode == 0, f"rank {rank}"
        m = re.search(r"ret=ok@(\d+)", outs[rank])
        assert m and int(m.group(1)) >= 1, outs[rank][-800:]
        assert re.search(r"world=2 iter=\d+", outs[rank]), outs[rank][-800:]
    # rank 2 shifted into rank 1's slot
    assert re.search(r"train start rank=1 world=2 iter=\d+", outs[2]), outs[2][-800:]


def test_hang_detected_and_killed(store_server):
    # STEPS=120 (6s of fn) keeps a wide margin between the hang kill
    # (~hard_timeout + interval ≈ 3s) and the survivor finishing its own
    # iteration 0 — on a loaded host a thin margin lets rank 0 complete
    # BEFORE the kill lands and no restart is observed
    procs, outs = run_scenario(
        store_server, "hang", world=2, timeout=150,
        extra_env={"SOFT_TIMEOUT": "1.0", "HARD_TIMEOUT": "2.5",
                   "STEPS": "120"},
    )
    if procs[0].returncode != 0:
        _dump(outs)
    # hung rank was killed by its monitor process
    assert procs[1].returncode != 0
    # survivor restarted alone and completed (iteration >= 1; load stalls
    # can fire extra legitimate restarts on the survivor's own budgets)
    assert procs[0].returncode == 0
    m = re.search(r"ret=ok@(\d+)", outs[0])
    assert m and int(m.group(1)) >= 1, outs[0][-800:]
    assert re.search(r"world=1 iter=\d+", outs[0]), outs[0][-800:]


def test_quorum_tripwire_restarts_without_host_timeouts(store_server):
    """VERDICT r2 #1: the on-device quorum trip must DRIVE recovery.

    Rank 1 stops beating (Python-level stall).  Every host-side detector is
    configured orders of magnitude too slow (soft 300s, hard 600s, sibling
    300s), so the ONLY path to the restart is: quorum collective observes the
    stale stamp -> QUORUM_STALE interruption record -> monitor threads trip
    -> async restart raise -> both ranks restart in-process and complete.
    """
    t0 = time.monotonic()
    procs, outs = run_scenario(
        store_server, "quorum_hang", world=2, timeout=150,
        extra_env={
            "SOFT_TIMEOUT": "300", "HARD_TIMEOUT": "600",
            "SIBLING_TIMEOUT": "300", "QUORUM_BUDGET_MS": "500",
        },
    )
    elapsed = time.monotonic() - t0
    if any(p.returncode != 0 for p in procs):
        _dump(outs)
    # BOTH ranks recovered in the same process (no kill; rc 0) and completed
    # at iteration >= 1 (a loaded host can stall the beater past the budget
    # and fire extra — legitimate — quorum restarts; the invariant is that
    # recovery HAPPENED and came from the quorum, not its exact count)
    for rank in (0, 1):
        assert procs[rank].returncode == 0
        m = re.search(r"ret=ok@(\d+)", outs[rank])
        assert m and int(m.group(1)) >= 1, outs[rank][-800:]
    # detection was the quorum's: the trip and the record kind are logged
    combined = outs[0] + outs[1]
    assert "quorum tripwire" in combined
    assert "quorum_stale" in combined
    # and it was FAST: far under the 300s host-timeout floor (compile +
    # restart dominate; detection itself is sub-second)
    assert elapsed < 120, elapsed


def test_late_fault_after_completion_exits_not_restarts(store_server):
    """Completion wins the completion-vs-fault race: when a peer finished
    the job in the same iteration, a faulted rank's restart path must exit
    (any_completed gate) rather than restart into an iteration barrier the
    completed peer will never attend (review r5 finding)."""
    procs, outs = run_scenario(store_server, "late_fault", world=2, timeout=60)
    if any(p.returncode != 0 for p in procs):
        _dump(outs)
    assert procs[0].returncode == 0
    assert "ret=done-early@0" in outs[0]
    assert procs[1].returncode == 0, outs[1][-800:]
    # the faulted rank exited via the completion gate, not a restart cycle.
    # The gate returns the JOB_COMPLETED sentinel (printed as
    # "ret=job-completed"); "ret=None" no longer exists as an outcome — it
    # used to be ambiguous with the layered-restart flake's lost-result
    # signature, where an async raise couldn't land inside a parked store op.
    assert "job completed" in outs[1], outs[1][-800:]
    assert "ret=job-completed" in outs[1], outs[1][-800:]
    assert "ret=None" not in outs[1], outs[1][-800:]


def test_spare_rank_activated_on_failure(store_server):
    procs, outs = run_scenario(
        store_server, "spare", world=3, timeout=120,
        extra_env={"MAX_ACTIVE": "2", "FAIL_RANK": "1", "SCENARIO2": ""},
    )
    # scenario "spare" with FAIL_RANK crashing? spare scenario only changes
    # assignment; make rank 1 crash via env:
    # (covered by the dedicated run below)
    for rank, p in enumerate(procs):
        if p.returncode != 0:
            _dump(outs)
        assert p.returncode == 0
    # rank 2 was INACTIVE initially, and the job completed
    assert "inactive" in outs[2].lower() or "RESULT" in outs[2]


def test_spare_promoted_after_crash(store_server):
    env = {"MAX_ACTIVE": "2", "FAIL_RANK": "1"}
    procs, outs = run_scenario(
        store_server, "spare_crash", world=3, timeout=150, extra_env=env
    )
    if procs[0].returncode != 0 or procs[2].returncode != 0:
        _dump(outs)
    assert procs[1].returncode == 31      # crashed
    assert procs[0].returncode == 0
    assert procs[2].returncode == 0
    # spare (initial rank 2) became active rank 1 (iteration >= 1)
    assert re.search(r"train start rank=1 world=2 iter=\d+", outs[2]), outs[2][-800:]
    m = re.search(r"ret=ok@(\d+)", outs[0])
    assert m and int(m.group(1)) >= 1, outs[0][-800:]


def test_tree_spare_promoted_into_gap(store_server):
    # 4 ranks = two 2-chip hosts; Tree(root RESERVE max_active=2,
    # host min=1 max=1): actives {0, 2}, spares {1, 3}.  Rank 2 crashes ->
    # its same-host spare (initial rank 3) takes over app rank 1.
    env = {"MAX_ACTIVE": "2", "FAIL_RANK": "2", "CHIPS_PER_HOST": "2"}
    procs, outs = run_scenario(
        store_server, "tree_crash", world=4, timeout=150, extra_env=env
    )
    if procs[0].returncode != 0 or procs[3].returncode != 0:
        _dump(outs)
    assert procs[1].returncode == 0      # parked spare, job completed
    assert procs[2].returncode == 31     # crashed
    assert procs[0].returncode == 0
    assert procs[3].returncode == 0
    # iteration number may exceed 1 under host load (extra legitimate
    # restarts); the invariant is the spare took app rank 1 in a world of 2
    assert re.search(r"train start rank=1 world=2 iter=\d+", outs[3]), outs[3][-800:]
    m = re.search(r"ret=ok@(\d+)", outs[0])
    assert m and int(m.group(1)) >= 1, outs[0][-800:]


def test_tree_host_loss_promotes_whole_spare_host(store_server):
    # host min=max=2: rank 1's crash terminates all of host0 (healthy rank 0
    # is discontinued and must mark itself so peers' barriers don't wait);
    # host1's spares take both slots.
    env = {"MAX_ACTIVE": "2", "FAIL_RANK": "1", "CHIPS_PER_HOST": "2"}
    procs, outs = run_scenario(
        store_server, "tree_hostcrash", world=4, timeout=150, extra_env=env
    )
    if procs[2].returncode != 0 or procs[3].returncode != 0:
        _dump(outs)
    assert procs[1].returncode == 31     # crashed
    assert procs[0].returncode == 7      # healthy but discontinued with host0
    assert "DISCONTINUED rank=0" in outs[0]
    assert procs[2].returncode == 0
    assert procs[3].returncode == 0
    assert re.search(r"train start rank=0 world=2 iter=\d+", outs[2]), outs[2][-800:]
    assert re.search(r"train start rank=1 world=2 iter=\d+", outs[3]), outs[3][-800:]
    m = re.search(r"ret=ok@(\d+)", outs[2])
    assert m and int(m.group(1)) >= 1, outs[2][-800:]


class TestActivateWholeGroups:
    def _policy(self):
        from tpu_resiliency.inprocess.rank_assignment import ActivateWholeGroups

        # 8 ranks, 4 per host
        return ActivateWholeGroups(key_of_rank=lambda r: r // 4, group_size=4)

    def test_all_groups_complete(self):
        p = self._policy()
        ctx = RankAssignmentCtx(_state(5, 8), set())
        p(ctx)
        assert ctx.state.mode == Mode.ACTIVE
        assert ctx.state.active_rank == 5
        assert ctx.state.active_world_size == 8

    def test_broken_group_parks_inactive(self):
        p = self._policy()
        # rank 6 died -> host 1 (ranks 4-7) incomplete; rank 5 parks
        ctx = RankAssignmentCtx(_state(5, 8), {6})
        p(ctx)
        assert ctx.state.mode == Mode.INACTIVE
        assert ctx.state.active_world_size == 4
        # host 0 members stay active with their ranks
        ctx0 = RankAssignmentCtx(_state(2, 8), {6})
        p(ctx0)
        assert ctx0.state.mode == Mode.ACTIVE
        assert ctx0.state.active_rank == 2

    def test_min_groups_enforced(self):
        from tpu_resiliency.inprocess.exceptions import RestartAbort
        from tpu_resiliency.inprocess.rank_assignment import ActivateWholeGroups

        p = ActivateWholeGroups(lambda r: r // 4, 4, min_groups=2)
        with pytest.raises(RestartAbort):
            p(RankAssignmentCtx(_state(0, 8), {6}))


def test_completion_and_terminate_hooks(store_server):
    """Completion transforms the return value; terminate fires on RestartAbort."""
    import threading

    from tpu_resiliency.inprocess import Wrapper
    from tpu_resiliency.inprocess.exceptions import RestartAbort
    from tpu_resiliency.store import StoreClient

    calls = {"completion": 0, "terminate": 0}

    def completion(state, ret):
        calls["completion"] += 1
        return ret + "!"

    def terminate(state):
        calls["terminate"] += 1

    def factory():
        return StoreClient("127.0.0.1", store_server.port, timeout=10.0)

    os.environ["TPURX_RANK"] = "0"
    os.environ["TPURX_WORLD_SIZE"] = "1"
    try:
        w1 = Wrapper(store_factory=factory, group="hooks1", completion=completion,
                     enable_monitor_process=False, enable_sibling_monitor=False)
        assert w1(lambda: "done")() == "done!"
        assert calls["completion"] == 1

        w2 = Wrapper(store_factory=factory, group="hooks2", terminate=terminate,
                     max_iterations=0,
                     enable_monitor_process=False, enable_sibling_monitor=False)
        with pytest.raises(RestartAbort):
            w2(lambda: "never")()
        assert calls["terminate"] == 1
    finally:
        os.environ.pop("TPURX_RANK", None)
        os.environ.pop("TPURX_WORLD_SIZE", None)
