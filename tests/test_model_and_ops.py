"""Model / mesh / quorum tests on the 8-device CPU mesh."""

import time

import jax
import numpy as np
import pytest

from tpu_resiliency.models.transformer import (
    TransformerConfig,
    init_opt_state,
    init_params,
    loss_fn,
    make_batch,
    make_train_step,
)
from tpu_resiliency.ops.quorum import QuorumMonitor, make_quorum_fn, now_stamp_ns
from tpu_resiliency.parallel.collectives import device_max_reduce, make_timeouts_reduce_fn
from tpu_resiliency.parallel.mesh import make_mesh

CFG = TransformerConfig(vocab=256, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=32)


def test_make_mesh_shapes():
    mesh = make_mesh(("data", "model"), (4, 2))
    assert mesh.shape == {"data": 4, "model": 2}
    mesh2 = make_mesh(("data", "model"), (-1, 2))
    assert mesh2.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh(("a",), (3,))


def test_forward_loss_finite():
    params = init_params(CFG)
    batch = make_batch(CFG, 2, 32)
    loss = loss_fn(params, batch, CFG)
    assert np.isfinite(float(loss))
    assert abs(float(loss) - np.log(CFG.vocab)) < 1.0  # random init ≈ uniform


def test_train_step_learns_sharded():
    mesh = make_mesh(("data", "model"), (4, 2))
    params = init_params(CFG, mesh=mesh)
    opt = init_opt_state(params)
    batch = make_batch(CFG, 8, 32, mesh=mesh)
    step = make_train_step(CFG, mesh=mesh, lr=1e-2)
    losses = []
    for _ in range(5):
        params, opt, loss = step(params, opt, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # memorizing a fixed batch
    # params kept their sharding through the step
    wq = params["layers"][0]["wq"]
    assert len(wq.sharding.device_set) == 8


def test_device_max_reduce_single_process():
    out = device_max_reduce([1.0, 5.0, -2.0])
    assert out == [1.0, 5.0, -2.0]
    fn = make_timeouts_reduce_fn()
    assert fn({"a": 3.0, "b": 7.0}) == {"a": 3.0, "b": 7.0}


def test_quorum_reduce_max_age():
    mesh = make_mesh(("all",), (8,))
    fn = make_quorum_fn(mesh, use_pallas=False)
    now = now_stamp_ns()
    stamps = np.full(8, now, dtype=np.int64)
    stamps[3] = now - 500_000_000  # one device 500ms stale
    age_ns = fn(stamps)
    assert 500_000_000 <= age_ns < 2_000_000_000, age_ns


def test_quorum_age_wrap_safe():
    """A hung rank's pre-wrap stamp must dominate fresh post-wrap stamps."""
    mesh = make_mesh(("all",), (8,))
    fn = make_quorum_fn(mesh, use_pallas=False)
    import tpu_resiliency.ops.quorum as q
    now = 100_000_000  # 100ms after the 2^63 wrap
    hung = q._WRAP_NS - 400_000_000  # beat 500ms ago, before the wrap
    orig = q.now_stamp_ns
    q.now_stamp_ns = lambda: now
    try:
        fn2 = make_quorum_fn(mesh, use_pallas=False)
        stamps = np.full(8, now - 1_000_000, dtype=np.int64)
        stamps[5] = hung
        age_ns = fn2(stamps)
        assert 400_000_000 <= age_ns < 800_000_000, age_ns
    finally:
        q.now_stamp_ns = orig


def test_quorum_identify_names_stale_device():
    """identify=True returns (age_ns, device_idx) from the SAME single int32
    pmax (host-side packing, ops/quorum.py::pack_age_device)."""
    mesh = make_mesh(("all",), (8,))
    fn = make_quorum_fn(mesh, use_pallas=False, identify=True)
    now = now_stamp_ns()
    stamps = np.full(8, now, dtype=np.int64)
    stamps[5] = now - 500_000_000  # 500ms: below the packed cap
    age_ns, dev = fn(stamps)
    assert 500_000_000 <= age_ns < 2_000_000_000, age_ns
    assert dev == 5
    # saturation: ages past the 15-bit cap still compare and identify
    stamps[2] = now - 10_000_000_000  # 10s >> ~1.07s cap
    age2, dev2 = fn(stamps)
    assert dev2 == 2
    from tpu_resiliency.ops.quorum import _AGE_CAP, units_to_ns
    assert age2 == units_to_ns(_AGE_CAP)


def test_quorum_monitor_identify_passes_device_to_on_stale():
    mesh = make_mesh(("all",), (8,))
    hits = []
    mon = QuorumMonitor(
        mesh, budget_ms=100.0, interval=0.01,
        on_stale=lambda age, dev: hits.append((age, dev)),
        use_pallas=False, identify=True,
    )
    mon.start()
    deadline = time.monotonic() + 5.0
    while not hits and time.monotonic() < deadline:
        time.sleep(0.01)
    mon.stop()
    assert hits
    age, dev = hits[0]
    assert age > 100
    assert 0 <= dev < 8


def test_quorum_monitor_detects_stale():
    mesh = make_mesh(("all",), (8,))
    hits = []
    mon = QuorumMonitor(
        mesh, budget_ms=100.0, interval=0.01,
        on_stale=lambda age: hits.append(age), use_pallas=False,
    )
    mon.start()
    # healthy while beating
    for _ in range(10):
        mon.beat()
        time.sleep(0.02)
    assert not hits
    # stop beating -> stale trip within budget + a few ticks
    t0 = time.monotonic()
    deadline = t0 + 5.0
    while not hits and time.monotonic() < deadline:
        time.sleep(0.01)
    mon.stop()
    assert hits
    latency_ms = (time.monotonic() - t0) * 1000
    assert latency_ms < 2000


def test_quorum_tick_pipelined():
    mesh = make_mesh(("all",), (8,))
    hits = []
    mon = QuorumMonitor(
        mesh, budget_ms=100.0, interval=0.01,
        on_stale=lambda age: hits.append(age), use_pallas=False,
    )
    mon.beat()
    assert mon.tick_pipelined() is None      # first call primes the pipe
    age1 = mon.tick_pipelined()
    assert age1 is not None and age1 < 100
    # stop beating; ages grow; stale fires once past budget (1-tick lag)
    time.sleep(0.15)
    mon.tick_pipelined()
    age = mon.tick_pipelined()
    assert age is not None and age >= 100
    assert hits


def test_quorum_overlapped_loop_and_calibrate():
    """fetch_workers>0: dispatches overlap result readbacks; calibrated
    budget derives from observed healthy ages; auto-beat keeps the pod
    healthy until stopped, then the stale trip fires."""
    mesh = make_mesh(("all",), (8,))
    hits = []
    mon = QuorumMonitor(
        mesh, budget_ms=1e9, interval=0.005,
        on_stale=lambda age: hits.append(age), use_pallas=False,
        auto_beat_interval=0.002, fetch_workers=4,
    )
    budget = mon.calibrate(n_ticks=8)
    assert budget >= 5.0
    mon.start()
    time.sleep(0.3)
    assert not hits, f"false trip on healthy pod: {hits}"
    assert mon.last_max_age is not None  # overlapped loop is evaluating
    mon.stop_auto_beat()
    t0 = time.monotonic()
    while not hits and time.monotonic() - t0 < 5.0:
        time.sleep(0.005)
    mon.stop()
    assert hits
    assert (time.monotonic() - t0) * 1000 < 2000


def test_quorum_dense_chain_and_load_calibration():
    """interval=0 (dense re-dispatched chain): the next collective
    dispatches as soon as a slot frees, so the cadence term of the
    detection floor collapses to the dispatch cost; calibrate(load_fn=...)
    samples healthy ages UNDER LOAD so a tight margin stays honest."""
    import jax

    from tpu_resiliency.parallel.mesh import make_mesh

    mesh = make_mesh(("all",), (len(jax.devices()),))
    hits = []
    loads = []
    mon = QuorumMonitor(
        mesh, budget_ms=1e9, interval=0.0,
        on_stale=lambda age: hits.append(age), use_pallas=False,
        auto_beat_interval=0.001, fetch_workers=4,
    )
    try:
        # default margin/floor: the test's subject is the dense loop and the
        # load_fn plumbing, not budget tightness — a deliberately tight
        # budget here would flake on loaded CI hosts
        budget = mon.calibrate(n_ticks=8, load_fn=lambda: loads.append(1))
        assert len(loads) == 8          # load ran before every sample
        assert budget >= 5.0
        mon.start()
        time.sleep(0.25)
        assert not hits, f"false trip on healthy pod: {hits}"
        assert mon.last_max_age is not None
        mon.stop_auto_beat()
        t0 = time.monotonic()
        while not hits and time.monotonic() - t0 < 5.0:
            time.sleep(0.002)
        assert hits
        # dense chain on a loaded host: generous bound, but far under the
        # pipelined loop's interval-dominated latency
        assert (time.monotonic() - t0) * 1000 < 2000
    finally:
        mon.stop()


def test_current_stamp_future_native_stamp_is_fresh():
    """ADVICE r5 regression: the native C thread can stamp NEWER than
    ``_current_stamp``'s ``now`` read between it and the slot read.  The
    folded age then lands near the half-wrap horizon and a naive
    wrap-compare would select a seconds-stale manual beat instead — a
    spurious trip.  Future stamps must be treated as fresh (age 0)."""
    import ctypes

    from tpu_resiliency.ops.quorum import _WRAP_NS

    # __new__: _current_stamp needs only the two stamp fields, and the full
    # constructor builds device collectives this logic test doesn't touch
    mon = QuorumMonitor.__new__(QuorumMonitor)
    now = now_stamp_ns()
    mon._last_beat_ns = (now - 10_000_000_000) % _WRAP_NS  # beat: 10s stale
    fut = (now + 50_000_000) % _WRAP_NS          # native slot: "the future"
    mon._native_slot = ctypes.c_int64(fut)
    assert mon._current_stamp() == fut           # pre-fix: stale manual beat
    # stale native + fresh manual: manual must still win
    mon._native_slot = ctypes.c_int64((now - 60_000_000_000) % _WRAP_NS)
    mon._last_beat_ns = now
    assert mon._current_stamp() == now
    # no native slot: manual beat passes through
    mon._native_slot = None
    assert mon._current_stamp() == now


def test_quorum_native_beater_stamps_and_freezes():
    """native_beat=True: a C pthread stamps the liveness slot (no GIL);
    stop_auto_beat freezes the slot so ages grow — the wedged-process
    simulation contract the bench and tests rely on.  Skips cleanly when
    the toolchain can't build the helper (python-beater fallback)."""
    import jax

    from tpu_resiliency.parallel.mesh import make_mesh

    mesh = make_mesh(("all",), (len(jax.devices()),))
    mon = QuorumMonitor(
        mesh, budget_ms=1e9, interval=0.01, use_pallas=False,
        auto_beat_interval=0.0005, native_beat=True,
    )
    try:
        mon._start_beater()
        if mon._native_beater is None or not mon._native_beater.alive:
            pytest.skip("native beat helper unavailable (no toolchain)")
        time.sleep(0.1)
        first = mon._native_slot.value
        assert first > 0
        time.sleep(0.05)
        assert mon._current_stamp() >= first
        age_live = mon.tick()
        assert age_live < 1000  # stamping keeps the pod fresh
        mon.stop_auto_beat()
        frozen = mon._native_slot.value
        time.sleep(0.25)
        assert mon._native_slot.value == frozen  # frozen: thread stopped
        age_stale = mon.tick()
        assert age_stale >= 200  # ages grow from the freeze instant
    finally:
        mon.stop()


def test_quorum_online_recalibration_under_load():
    """After N in-vivo healthy ticks, the budget is recomputed from ages
    observed UNDER the real workload (idle pre-start calibration undershoots
    busy-interpreter stamp lateness); tripping ages are excluded so a real
    hang cannot inflate its own detection budget."""
    import jax

    from tpu_resiliency.parallel.mesh import make_mesh

    mesh = make_mesh(("all",), (len(jax.devices()),))
    mon = QuorumMonitor(
        mesh, budget_ms=1000.0, interval=0.005, use_pallas=False,
        auto_beat_interval=0.001, online_recalibrate_after=10,
        online_min_budget_ms=2.0,
    )
    try:
        mon.beat()
        # feed synthetic healthy ages through the observation hook
        for age in [1.0, 1.2, 0.8, 1.1, 2.0, 1.4, 0.9, 1.3, 1.1]:
            mon._observe_healthy_age(age)
        assert not mon._recal_done
        mon._observe_healthy_age(1.6)   # 10th sample completes the window
        assert mon._recal_done
        # budget = max(floor, 3*p99 + 2) with p99 = 2.0 -> 8.0
        assert abs(mon.budget_ms - 8.0) < 1e-6
        # further observations are no-ops
        mon._observe_healthy_age(500.0)
        assert abs(mon.budget_ms - 8.0) < 1e-6
    finally:
        mon.stop()


def test_quorum_online_recalibration_excludes_tripping_ages():
    import jax

    from tpu_resiliency.parallel.mesh import make_mesh

    mesh = make_mesh(("all",), (len(jax.devices()),))
    mon = QuorumMonitor(
        mesh, budget_ms=10.0, interval=0.005, use_pallas=False,
        online_recalibrate_after=3,
    )
    try:
        mon._observe_healthy_age(5000.0)   # tripping age: excluded
        assert not mon._recal_ages
        for age in [1.0, 1.0, 1.0]:
            mon._observe_healthy_age(age)
        assert mon._recal_done
        assert mon.budget_ms == max(2.0, 3.0 * 1.0 + 2.0)
    finally:
        mon.stop()


def test_calibrate_floor_release_and_p99_export():
    """min_budget_ms releases the operator floor; the measured healthy p99
    is exported for the bench's floor-accounting (beat_jitter_p99_ms)."""
    import jax

    from tpu_resiliency.ops.quorum import QuorumMonitor
    from tpu_resiliency.parallel.mesh import make_mesh

    mesh = make_mesh(("all",), (len(jax.devices()),))
    mon = QuorumMonitor(mesh, budget_ms=1e9, interval=0.01,
                        auto_beat_interval=0.001)
    try:
        budget = mon.calibrate(n_ticks=8, min_budget_ms=1.0)
        assert budget >= 1.0
        assert mon.last_calibration_p99_ms is not None
        assert mon.last_calibration_p99_ms >= 0.0
        # the formula: budget = max(floor, safety*p99 + margin)
        assert budget >= 3.0 * mon.last_calibration_p99_ms
        # a high operator floor binds
        assert mon.calibrate(n_ticks=8, min_budget_ms=500.0) >= 500.0
    finally:
        mon.stop()
