"""Straggler detection tests (reference analog: tests/straggler/unit/* with
synthetic timing data + a live multi-threaded gather)."""

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from tpu_resiliency.straggler import Detector, Report
from tpu_resiliency.straggler.timers import DurationStore, SectionStats
from tpu_resiliency.store import StoreClient


def make_stats(name, base, n=11):
    return SectionStats.from_samples(name, [base * (1 + 0.01 * i) for i in range(n)])


class TestScoring:
    def test_section_stats(self):
        st = SectionStats.from_samples("s", [1.0, 2.0, 3.0, 4.0, 5.0])
        assert st.count == 5
        assert st.median == 3.0
        assert st.min == 1.0 and st.max == 5.0
        assert st.avg == 3.0

    def test_relative_scores_flag_slow_rank(self):
        # rank 2 is 2x slower on the dominant op
        per_rank = {
            0: {"step": make_stats("step", 0.10), "io": make_stats("io", 0.01)},
            1: {"step": make_stats("step", 0.11), "io": make_stats("io", 0.01)},
            2: {"step": make_stats("step", 0.20), "io": make_stats("io", 0.01)},
        }
        report = Report(0, section_stats={}, device_stats=per_rank)
        scores = report.relative_device_scores()
        assert scores[0] > 0.95
        assert scores[2] < 0.6
        verdicts = report.identify_stragglers(relative_threshold=0.7)
        flagged = [v.rank for v in verdicts if v.is_straggler]
        assert flagged == [2]

    def test_weighting_by_total_time(self):
        # rank 1 slow only on a negligible op -> not a straggler
        per_rank = {
            0: {"step": make_stats("step", 0.10), "tiny": make_stats("tiny", 0.001)},
            1: {"step": make_stats("step", 0.10), "tiny": make_stats("tiny", 0.01)},
        }
        report = Report(0, {}, per_rank)
        scores = report.relative_device_scores()
        assert scores[1] > 0.85

    def test_individual_scores(self):
        current = {"step": make_stats("step", 0.2)}
        history = {"step": 0.1}
        score = Report.individual_scores(current, history)
        assert score == pytest.approx(0.5, rel=0.05)
        assert Report.individual_scores({}, {}) is None

    def test_disjoint_names_across_ranks(self):
        per_rank = {
            0: {"a": make_stats("a", 0.1)},
            1: {"b": make_stats("b", 0.1)},
        }
        report = Report(0, {}, per_rank)
        scores = report.relative_device_scores()
        assert scores[0] == pytest.approx(1.0)
        assert scores[1] == pytest.approx(1.0)


def test_detector_sections_and_device_wrap():
    det = Detector(report_interval=4)
    det.initialize()

    @jax.jit
    def fn(x):
        return (x @ x).sum()

    wrapped = det.wrap_callables({"matmul": fn})["matmul"]
    x = jnp.ones((64, 64))
    report = None
    for i in range(8):
        with det.detection_section("host_work"):
            time.sleep(0.002)
        wrapped(x)
        report = report or det.maybe_report()
    assert report is not None
    assert "host_work" in report.section_stats[0]
    assert "matmul" in report.device_stats[0]
    assert report.device_stats[0]["matmul"].count >= 4
    assert det.individual_score() is not None


def test_multi_rank_gather_flags_straggler(store_server):
    world = 3
    results = {}

    def member(rank):
        store = StoreClient("127.0.0.1", store_server.port, timeout=20.0)
        det = Detector(
            store=store, rank=rank, world_size=world,
            report_interval=5, gather_on_rank0=True,
        )
        det.initialize()
        delay = 0.03 if rank == 1 else 0.01   # rank 1 is the straggler
        report = None
        for _ in range(5):
            with det.detection_section("step"):
                time.sleep(delay)
            r = det.maybe_report()
            report = r or report
        results[rank] = report
        store.close()

    threads = [threading.Thread(target=member, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert results[1] is None and results[2] is None  # gather_on_rank0
    report = results[0]
    assert report is not None
    verdicts = report.identify_stragglers(relative_threshold=0.7)
    flagged = [v.rank for v in verdicts if v.is_straggler]
    assert flagged == [1]


def test_xla_profile_collector_records_ops():
    """Per-op durations from a real jax.profiler trace (CUPTI analog)."""
    from tpu_resiliency.straggler.xla_profile import XlaProfileCollector
    from tpu_resiliency.straggler.timers import DurationStore
    import jax.numpy as jnp

    store = DurationStore()
    collector = XlaProfileCollector(store)

    @jax.jit
    def step(x):
        return (x @ x).sum()

    x = jnp.ones((128, 128))
    jax.block_until_ready(step(x))  # compile outside the capture
    with collector.capture():
        jax.block_until_ready(step(x))
    names = store.names()
    assert names, "no op durations captured"
    assert all(n.startswith("xla:") for n in names)
    # no python host frames leaked into device stats
    assert not any("$" in n for n in names)
    stats = store.stats()
    assert all(s.total > 0 for s in stats.values())


def test_detector_profiled_step():
    import jax.numpy as jnp

    det = Detector(report_interval=2)
    det.initialize()

    @jax.jit
    def step(x):
        return (x @ x).sum()

    x = jnp.ones((128, 128))
    jax.block_until_ready(step(x))
    with det.profiled_step():
        jax.block_until_ready(step(x))
    assert any(n.startswith("xla:") for n in det.device.names())


def test_op_diff_pinpoints_slow_op():
    per_rank = {
        0: {"matmul": make_stats("matmul", 0.10), "io": make_stats("io", 0.02)},
        1: {"matmul": make_stats("matmul", 0.30), "io": make_stats("io", 0.02)},
    }
    report = Report(0, {}, per_rank)
    diff = report.op_diff(1)
    assert diff[0]["name"] == "matmul"           # the dominant regression
    assert diff[0]["slowdown"] == pytest.approx(3.0, rel=0.05)
    assert diff[0]["time_lost"] > 0
    # the fastest rank shows no losses
    assert all(d["time_lost"] == 0 for d in report.op_diff(0))


# -- always-on collector (native rings, CUPTI-buffer analog) ----------------


def test_op_ring_arena_roundtrip():
    from tpu_resiliency.straggler import OpRingArena

    arena = OpRingArena(max_ops=4, capacity=8)
    try:
        idx = arena.intern("matmul")
        assert idx >= 0
        assert arena.intern("matmul") == idx  # stable re-intern
        for v in [1.0, 2.0, 3.0]:
            arena.push(idx, v)
        st = arena.stats()["matmul"]
        assert st.count == 3
        assert st.median == 2.0
        assert st.min == 1.0 and st.max == 3.0
        # circular window: push past capacity, window stays bounded
        for v in range(20):
            arena.push(idx, float(v))
        st = arena.stats()["matmul"]
        assert st.count == 8  # window, not lifetime
        arena.add_drop(idx)
        assert arena.drops()["matmul"] == 1
    finally:
        arena.close()


def test_op_ring_arena_full_is_bounded():
    from tpu_resiliency.straggler import OpRingArena

    arena = OpRingArena(max_ops=2, capacity=4)
    try:
        assert arena.intern("a") >= 0
        assert arena.intern("b") >= 0
        assert arena.intern("c") == -1  # full: bounded by design
        arena.push("a", 1.0)  # name-based push still works
        assert arena.stats()["a"].count == 1
    finally:
        arena.close()


def test_op_ring_cross_process_attach():
    """The rank monitor must be able to read a (possibly wedged) trainer's
    rings from OUTSIDE the process — the CUPTI buffers-outlive-the-launch
    property."""
    import subprocess
    import sys

    from tpu_resiliency.straggler import OpRingArena

    arena = OpRingArena(max_ops=8, capacity=16)
    if not arena.native:
        arena.close()
        pytest.skip("native ring library unavailable")
    try:
        idx = arena.intern("train_step")
        for v in [0.5, 1.5, 2.5]:
            arena.push(idx, v)
        code = (
            "from tpu_resiliency.straggler import OpRingArena\n"
            f"a = OpRingArena.attach({arena.shm_name!r})\n"
            "st = a.stats()['train_step']\n"
            "assert st.count == 3, st\n"
            "assert abs(st.median - 1.5) < 1e-6, st\n"
            "a.close()\n"
            "print('attached-ok')\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=60, cwd=str(__import__('pathlib').Path(__file__).parent.parent),
        )
        assert "attached-ok" in out.stdout, out.stderr
    finally:
        arena.close()


def test_op_collector_nonblocking_wrap():
    from tpu_resiliency.straggler import OpCollector

    coll = OpCollector()
    try:

        @jax.jit
        def step(x):
            return (x @ x).sum()

        x = jnp.ones((64, 64))
        jax.block_until_ready(step(x))
        wrapped = coll.wrap(step, "step")
        for _ in range(10):
            out = wrapped(x)
        jax.block_until_ready(out)
        coll.flush(timeout=10.0)
        st = coll.stats()["step"]
        assert st.count == 10
        assert st.total > 0
        assert sum(coll.drops().values()) == 0
    finally:
        coll.close()


def test_op_collector_duty_cycle_profile():
    """profile_interval_s elapsed -> ONE call runs under the profiler and
    intra-module per-op durations land in the rings under xla: names."""
    from tpu_resiliency.straggler import OpCollector

    coll = OpCollector(profile_interval_s=0.01)
    try:

        @jax.jit
        def step(x):
            return (x @ x).sum()

        x = jnp.ones((128, 128))
        jax.block_until_ready(step(x))
        wrapped = coll.wrap(step, "step")
        time.sleep(0.05)  # make the duty cycle due
        wrapped(x)  # the profiled call
        wrapped(x)
        coll.flush(timeout=10.0)
        names = coll.stats().keys()
        assert any(n.startswith("xla:") for n in names), names
        assert coll.lane_filter_misses == 0
    finally:
        coll.close()


def test_op_collector_python_fallback(monkeypatch):
    import tpu_resiliency.straggler.collector as collector_mod

    monkeypatch.setattr(collector_mod, "_load_ring_lib", lambda: None)
    arena = collector_mod.OpRingArena(max_ops=4, capacity=8)
    try:
        assert not arena.native
        idx = arena.intern("op")
        for v in [1.0, 3.0]:
            arena.push(idx, v)
        st = arena.stats()["op"]
        assert st.count == 2 and st.avg == 2.0
        arena.add_drop(idx)
        assert arena.drops()["op"] == 1
    finally:
        arena.close()


def test_detector_always_on_collector_in_report():
    det = Detector(report_interval=4, always_on=True)
    det.initialize()
    assert det.collector is not None

    @jax.jit
    def step(x):
        return (x @ x).sum()

    x = jnp.ones((64, 64))
    jax.block_until_ready(step(x))
    fns = det.wrap_callables({"train": step})
    for _ in range(6):
        out = fns["train"](x)
    jax.block_until_ready(out)
    report = det.generate_report()
    assert report is not None
    st = report.device_stats[0].get("train")
    assert st is not None and st.count == 6
    det.shutdown()


def test_opring_inspect_cli():
    """tpurx-opring renders a live arena's per-op table from the shell."""
    from tpu_resiliency.straggler import OpRingArena
    from tpu_resiliency.straggler.inspect import render

    arena = OpRingArena(max_ops=8, capacity=32)
    if not arena.native:
        arena.close()
        pytest.skip("native ring library unavailable")
    try:
        for name, vals in (("train_step", [0.1, 0.2, 0.3]),
                           ("xla:fusion.1", [0.05])):
            idx = arena.intern(name)
            for v in vals:
                arena.push(idx, v)
        out = render(arena.shm_name)
        assert "train_step" in out and "xla:fusion.1" in out
        import re

        # count column specifically (not a digit from the shm name/durations)
        assert re.search(r"train_step\s+3\s", out), out
        from tpu_resiliency.straggler import OpRingArena as _A

        assert _A.looks_like_arena(arena.shm_name)
        # cross-process, like the operator would use it
        import subprocess
        import sys as _sys

        proc = subprocess.run(
            [_sys.executable, "-m", "tpu_resiliency.straggler.inspect",
             arena.shm_name],
            capture_output=True, text=True, timeout=60,
            cwd=str(__import__("pathlib").Path(__file__).parent.parent),
        )
        assert proc.returncode == 0, proc.stderr
        assert "train_step" in proc.stdout
    finally:
        arena.close()


def test_opring_inspect_from_pid():
    """--from-pid finds the arena via the trainer's shm MAPPINGS (the env
    var is runtime-only and invisible in /proc/<pid>/environ)."""
    import subprocess
    import sys as _sys

    from tpu_resiliency.straggler import OpRingArena

    probe = OpRingArena(max_ops=2, capacity=4)
    native = probe.native
    probe.close()
    if not native:
        pytest.skip("native ring library unavailable")

    code = (
        "import sys, time\n"
        "sys.path.insert(0, '.')\n"
        "from tpu_resiliency.straggler import OpRingArena\n"
        "a = OpRingArena(max_ops=4, capacity=8)\n"
        "a.push(a.intern('stuck_op'), 1.25)\n"
        "print(a.shm_name, flush=True)\n"
        "time.sleep(60)\n"
    )
    trainer = subprocess.Popen(
        [_sys.executable, "-c", code], stdout=subprocess.PIPE, text=True,
        cwd=str(__import__("pathlib").Path(__file__).parent.parent),
    )
    try:
        shm_name = trainer.stdout.readline().strip()
        assert shm_name
        out = subprocess.run(
            [_sys.executable, "-m", "tpu_resiliency.straggler.inspect",
             "--from-pid", str(trainer.pid)],
            capture_output=True, text=True, timeout=60,
            cwd=str(__import__("pathlib").Path(__file__).parent.parent),
        )
        assert out.returncode == 0, out.stderr
        assert "stuck_op" in out.stdout
    finally:
        trainer.kill()
        trainer.wait()
