"""Predict-and-evacuate tests (ISSUE 18): noisy-OR rank risk fusion and
its damping, the straggler-gauge → feed → estimator attribution path (a
synthetic slow rank must move the estimator's risk output), the
controller's streak/hysteresis evacuation trigger, the one-shot actuator
action and its per-rank replay dispatch, the pipeline's
checkpoint-ahead → promote → shrink stages with bounded store records,
the warm-join deadline, and the merged-trace rendering of evacuation
spans."""

import json
import threading

import pytest

from tpu_resiliency.policy import (
    Action,
    Actuator,
    EstimatorInputs,
    EvacuationPipeline,
    GoodputEstimator,
    PolicyController,
    RankRiskModel,
    RankSignals,
    SnapshotFeed,
    TelemetryFeed,
    set_evacuation_handler,
    _reset_ledger_for_tests,
)
from tpu_resiliency.policy import evacuation as evac_mod
from tpu_resiliency.telemetry import episode as episode_mod
from tpu_resiliency.telemetry import trace
from tpu_resiliency.telemetry.registry import Registry
from tpu_resiliency.utils import env


@pytest.fixture(autouse=True)
def _clean_evac_state():
    """Fresh overrides/ledger/episode/handler state around every test."""
    env.clear_runtime_overrides()
    _reset_ledger_for_tests()
    set_evacuation_handler(None)
    episode_mod._current = None
    yield
    env.clear_runtime_overrides()
    _reset_ledger_for_tests()
    set_evacuation_handler(None)
    episode_mod._current = None


class _FakeStore:
    def __init__(self):
        self.data = {}
        self.counters = {}

    def set(self, key, value):
        self.data[key] = value

    def add(self, key, amount):
        self.counters[key] = self.counters.get(key, 0) + amount
        return self.counters[key]

    def delete(self, key):
        self.data.pop(key, None)

    def try_get(self, key):
        return self.data.get(key)

    def list_keys(self, prefix):
        return [k for k in self.data if k.startswith(prefix)]


# ---- RankRiskModel ----------------------------------------------------------


class TestRankRiskModel:
    def test_single_saturated_indicator_is_sufficient(self):
        """Noisy-OR: health pegged at 1.0 alone drives the fused score to
        1.0 (damped toward it tick by tick)."""
        m = RankRiskModel(window_s=60.0)
        sig = {1: RankSignals(health_score=1.0)}
        assert m.update(sig, now=0.0)[1] == pytest.approx(0.5)
        assert m.update(sig, now=5.0)[1] == pytest.approx(0.75)
        assert m.update(sig, now=10.0)[1] == pytest.approx(0.875)

    def test_independent_indicators_compound(self):
        """Two moderate signals fuse above either alone: noisy-OR, not
        averaging."""
        both = RankRiskModel.fuse(
            RankSignals(health_score=0.5, straggler_score=0.5), 0.0
        )
        health_only = RankRiskModel.fuse(RankSignals(health_score=0.5), 0.0)
        strag_only = RankRiskModel.fuse(
            RankSignals(straggler_score=0.5), 0.0
        )
        assert both == pytest.approx(0.7)
        assert both > health_only and both > strag_only

    def test_straggler_alone_is_capped(self):
        """A dead-slow rank (score 0) is not certain death: the straggler
        component saturates below 1."""
        raw = RankRiskModel.fuse(RankSignals(straggler_score=0.0), 0.0)
        assert raw == pytest.approx(0.8)

    def test_route_bias_discounted(self):
        raw = RankRiskModel.fuse(RankSignals(route_bias=1.0), 0.0)
        assert raw == pytest.approx(0.6)

    def test_kmsg_hard_fault_saturates_component(self):
        """One hard kmsg fault inside the window pegs that component."""
        m = RankRiskModel(window_s=60.0)
        m.update({0: RankSignals(kmsg_hard_total=0.0)}, now=0.0)
        scores = m.update({0: RankSignals(kmsg_hard_total=1.0)}, now=10.0)
        # raw fused = 1.0, EWMA from 0 → 0.5 on this tick
        assert scores[0] == pytest.approx(0.5)

    def test_absent_rank_decays_and_forget_clears(self):
        m = RankRiskModel(window_s=60.0)
        m.update({2: RankSignals(health_score=1.0)}, now=0.0)
        m.update({2: RankSignals(health_score=1.0)}, now=5.0)
        high = m.scores[2]
        m.update({}, now=10.0)
        m.update({}, now=15.0)
        assert m.scores[2] < high
        m.forget(2)
        assert 2 not in m.scores
        assert m.worst() == (None, 0.0)

    def test_deadband_suppresses_flutter(self):
        m = RankRiskModel(window_s=60.0)
        m.update({0: RankSignals(health_score=0.5)}, now=0.0)
        for t in range(1, 30):
            m.update({0: RankSignals(health_score=0.5)}, now=float(t))
        settled = m.scores[0]
        # a sub-deadband wiggle in the raw signal publishes nothing new
        m.update({0: RankSignals(health_score=0.51)}, now=31.0)
        assert m.scores[0] == settled

    def test_worst_picks_riskiest_rank(self):
        m = RankRiskModel(window_s=60.0)
        m.update(
            {
                0: RankSignals(health_score=0.2),
                3: RankSignals(health_score=0.9),
            },
            now=0.0,
        )
        rank, score = m.worst()
        assert rank == 3 and score == pytest.approx(0.45)


# ---- satellite 1: straggler gauge → feed → estimator risk -------------------


class TestStragglerRiskAttribution:
    def test_synthetic_slow_rank_moves_estimator_risk(self):
        """The published ``tpurx_straggler_score{rank}`` gauge must reach
        the estimator: a synthetic slow rank raises that rank's fused
        risk (and the node risk the hardening rung keys off), attributed
        to the right rank."""
        reg = Registry(enabled=True)
        feed = TelemetryFeed(registry=reg, rank=0)
        est = GoodputEstimator(window_s=60.0)
        est.update(feed.collect(), now=0.0)
        baseline = dict(est.rank_risk)
        assert est.node_risk == 0.0

        score = reg.gauge(
            "tpurx_straggler_score", "individual score", labels=("rank",)
        )
        score.labels("1").set(0.2)   # rank 1 running at 20% of nominal
        score.labels("0").set(1.0)
        for t in (5.0, 10.0, 15.0):
            est.update(feed.collect(), now=t)
        assert est.rank_risk[1] > baseline.get(1, 0.0)
        assert est.rank_risk[1] > 0.5
        assert est.rank_risk.get(0, 0.0) == pytest.approx(0.0)
        assert est.worst_rank()[0] == 1
        assert est.node_risk == pytest.approx(est.rank_risk[1])

    def test_snapshot_feed_attributes_signals_per_rank(self):
        """Cross-rank shape: each rank's snapshot carries its own node
        health; straggler scores ride the {rank} label on the report
        holder's snapshot and are assigned by label, not by holder."""
        snaps = {
            0: {
                "tpurx_straggler_score": {
                    "samples": [
                        {"labels": {"rank": "0"}, "value": 1.0},
                        {"labels": {"rank": "1"}, "value": 0.3},
                    ]
                },
            },
            1: {
                "tpurx_health_score": {
                    "samples": [{"labels": {"check": "ecc"}, "value": 0.9}]
                },
            },
        }
        signals = SnapshotFeed._rank_signals(snaps)
        assert signals[1].health_score == pytest.approx(0.9)
        assert signals[1].straggler_score == pytest.approx(0.3)
        assert signals[0].health_score == 0.0
        assert signals[0].straggler_score == pytest.approx(1.0)

    def test_empty_rank_signals_preserve_node_risk_semantics(self):
        """Backward compatibility: with no per-rank signals the estimator
        carries the legacy gauge-fed node risk unchanged."""
        est = GoodputEstimator(window_s=60.0)
        est.update(EstimatorInputs(node_risk=0.4), now=0.0)
        assert est.node_risk == pytest.approx(0.4)
        assert est.rank_risk == {}


# ---- controller trigger -----------------------------------------------------


def _risky_inputs(rank=1, health=1.0):
    return EstimatorInputs(
        rank_signals={rank: RankSignals(health_score=health)}
    )


class _ScriptedFeed:
    def __init__(self, script):
        self.script = list(script)
        self.i = 0

    def collect(self):
        inputs = self.script[min(self.i, len(self.script) - 1)]
        self.i += 1
        return inputs


class TestControllerEvacuate:
    def test_disabled_by_default(self):
        ctl = PolicyController(
            feed=_ScriptedFeed([_risky_inputs()]),
            estimator=GoodputEstimator(window_s=60.0),
        )
        for t in range(6):
            actions = ctl.tick(now=float(t * 5))
            assert not [a for a in actions if a.kind == "evacuate"]

    def test_fires_after_streak_and_is_one_shot(self):
        env.set_runtime_override(env.EVAC.name, "1")
        fired = []
        set_evacuation_handler(lambda rank, reason: fired.append(rank))
        ctl = PolicyController(
            feed=_ScriptedFeed([_risky_inputs(rank=1)]),
            estimator=GoodputEstimator(window_s=60.0),
        )
        evacs = []
        for t in range(8):
            evacs += [
                a for a in ctl.tick(now=float(t * 5)) if a.kind == "evacuate"
            ]
        # EWMA crosses 0.7 on tick 2; streak guard delays the fire one
        # more tick; the actuator one-shot stops any repeat
        assert len(evacs) == 1
        assert evacs[0].target == "rank:1" and evacs[0].value == "1"
        assert fired == [1]

    def test_streak_resets_on_dip(self):
        """A single over-threshold tick followed by recovery never
        evacuates (false-positive guard)."""
        env.set_runtime_override(env.EVAC.name, "1")
        script = (
            [_risky_inputs(rank=1, health=1.0)] * 2    # risk reaches ~0.75
            + [_risky_inputs(rank=1, health=0.0)] * 10  # decays back down
        )
        ctl = PolicyController(
            feed=_ScriptedFeed(script),
            estimator=GoodputEstimator(window_s=60.0),
        )
        evacs = []
        for t in range(12):
            evacs += [
                a for a in ctl.tick(now=float(t * 5)) if a.kind == "evacuate"
            ]
        assert evacs == []
        assert ctl._evac_streak.get(1, 0) == 0

    def test_healthy_ranks_never_evacuated(self):
        """Moderate, steady signals below threshold must not trigger."""
        env.set_runtime_override(env.EVAC.name, "1")
        inputs = EstimatorInputs(
            rank_signals={
                0: RankSignals(health_score=0.3, straggler_score=0.9),
                1: RankSignals(health_score=0.2),
            }
        )
        ctl = PolicyController(
            feed=_ScriptedFeed([inputs]),
            estimator=GoodputEstimator(window_s=60.0),
        )
        for t in range(20):
            actions = ctl.tick(now=float(t * 5))
            assert not [a for a in actions if a.kind == "evacuate"]

    def test_hardening_armed_at_or_before_evacuation(self):
        """The fused rank risk feeds node risk, so replication/delta
        hardening arms on the same tick the risk crosses — never after
        the evacuation decision."""
        env.set_runtime_override(env.EVAC.name, "1")
        ctl = PolicyController(
            feed=_ScriptedFeed([_risky_inputs(rank=1)]),
            estimator=GoodputEstimator(window_s=60.0),
        )
        seen = []
        for t in range(6):
            for a in ctl.tick(now=float(t * 5)):
                seen.append(a.kind)
        assert "evacuate" in seen
        assert seen.index("set_replication") < seen.index("evacuate")

    def test_rearm_latch_follows_hysteresis_band(self):
        env.set_runtime_override(env.EVAC.name, "1")
        script = (
            [_risky_inputs(rank=1, health=1.0)] * 4
            + [_risky_inputs(rank=1, health=0.0)] * 20
        )
        ctl = PolicyController(
            feed=_ScriptedFeed(script),
            estimator=GoodputEstimator(window_s=60.0),
        )
        for t in range(4):
            ctl.tick(now=float(t * 5))
        assert ctl._evac_armed.get(1) is False  # latched after the fire
        for t in range(4, 24):
            ctl.tick(now=float(t * 5))
        # risk decayed below threshold·(1−hysteresis): latch re-arms
        assert ctl._evac_armed.get(1) is True


# ---- actuator ---------------------------------------------------------------


class TestActuatorEvacuate:
    def test_one_shot_per_rank(self):
        act = Actuator()
        first = act.evacuate(2, "risk 0.9")
        assert first is not None and first.kind == "evacuate"
        assert first.target == "rank:2"
        assert act.evacuate(2, "risk 0.95") is None
        assert act.evacuate(3, "risk 0.9") is not None

    def test_apply_dispatches_to_handler_once(self):
        fired = []
        set_evacuation_handler(lambda rank, reason: fired.append((rank, reason)))
        act = Actuator()
        action = Action("evacuate", "rank:3", "3", "published decision")
        act.apply(action)
        act.apply(action)  # replayed decision must not double-evacuate
        assert fired == [(3, "published decision")]

    def test_apply_knob_actions_unaffected(self):
        act = Actuator()
        act.apply(Action("set_cadence", env.CKPT_INTERVAL_S.name, "42.0", "t"))
        assert env.CKPT_INTERVAL_S.get() == pytest.approx(42.0)

    def test_evacuate_without_handler_is_journal_only(self):
        act = Actuator()
        assert act.evacuate(1, "no handler installed") is not None


# ---- pipeline ---------------------------------------------------------------


class TestEvacuationPipeline:
    def _pipeline(self, store=None, **kw):
        kw.setdefault("save_fn", lambda: kw.setdefault("_saved", True))
        return EvacuationPipeline(store=store, rank=0, **kw)

    def test_stages_run_and_record_published(self):
        store = _FakeStore()
        calls = []
        pipe = EvacuationPipeline(
            store=store,
            rank=0,
            save_fn=lambda: calls.append("save"),
            promote_fn=lambda victim: calls.append("promote") or "h:9",
            shrink_fn=lambda victim: calls.append(f"shrink:{victim}") or "ok",
        )
        record = pipe.evacuate(1, risk=0.84, reason="test")
        assert calls == ["save", "promote", "shrink:1"]
        assert record["victim_rank"] == 1 and record["spare"] == "h:9"
        # checkpoint-ahead bumped replication for the handoff
        assert env.LCKPT_REPLICATION.get() >= 3
        published = json.loads(store.data["evac/1/record"])
        assert published["victim_rank"] == 1
        assert published["episode"].startswith("ep")

    def test_episode_phases_include_evacuate_with_exact_coverage(self):
        store = _FakeStore()
        pipe = EvacuationPipeline(
            store=store, rank=0, shrink_fn=lambda victim: None
        )
        pipe.evacuate(1, risk=0.9)
        summaries = [
            k for k in store.data if k.startswith("episode/ep")
        ]
        assert summaries, "episode summary not published"
        summary = json.loads(store.data[summaries[0]])
        assert summary["fault_class"] == "evacuation"
        assert "evacuate" in summary["phases_ns"]
        assert summary["coverage_pct"] == pytest.approx(100.0, abs=0.5)

    def test_record_window_is_bounded(self):
        store = _FakeStore()
        pipe = EvacuationPipeline(
            store=store, rank=0, shrink_fn=lambda victim: None, keep=2
        )
        for victim in (1, 2, 3):
            episode_mod._current = None
            pipe.evacuate(victim, risk=0.9)
        assert "evac/1/record" not in store.data
        assert "evac/2/record" in store.data and "evac/3/record" in store.data

    def test_failed_stage_raises_and_records_error(self):
        store = _FakeStore()

        def _boom(victim):
            raise RuntimeError("promotion lost the CAS race")

        pipe = EvacuationPipeline(
            store=store, rank=0, promote_fn=_boom,
            shrink_fn=lambda victim: None,
        )
        with pytest.raises(RuntimeError):
            pipe.evacuate(1, risk=0.9)
        published = json.loads(store.data["evac/1/record"])
        assert "promotion lost the CAS race" in published["error"]

    def test_nonvictim_shrink_is_noop(self):
        """Default shrink path: every rank but the victim returns
        immediately (survivors keep training)."""
        pipe = EvacuationPipeline(store=None, rank=0)
        record = pipe.evacuate(1, risk=0.9)  # we are rank 0, victim is 1
        assert record["shrink"] is None


# ---- warm join --------------------------------------------------------------


class _FakeManager:
    def __init__(self, result=("tree", 7), error=None, block=None):
        self.result = result
        self.error = error
        self.block = block

    def load(self, template, iteration=None):
        if self.block is not None:
            self.block.wait()
        if self.error is not None:
            raise self.error
        return self.result


class TestWarmJoin:
    def test_warm_when_no_disk_bytes(self, monkeypatch):
        sources = iter([{}, {"peer_memory": 4096.0}])
        monkeypatch.setattr(
            evac_mod, "_restore_source_bytes", lambda: next(sources)
        )
        pipe = EvacuationPipeline(store=None, rank=2)
        out = pipe.warm_join(_FakeManager(), template={}, timeout=5.0)
        assert out["warm"] is True
        assert out["iteration"] == 7
        assert out["source_bytes"] == {"peer_memory": 4096.0}

    def test_cold_when_disk_rung_served(self, monkeypatch):
        sources = iter([{}, {"peer_memory": 10.0, "peer_disk": 4086.0}])
        monkeypatch.setattr(
            evac_mod, "_restore_source_bytes", lambda: next(sources)
        )
        pipe = EvacuationPipeline(store=None, rank=2)
        out = pipe.warm_join(_FakeManager(), template={}, timeout=5.0)
        assert out["warm"] is False

    def test_deadline_raises_timeout(self):
        gate = threading.Event()
        pipe = EvacuationPipeline(store=None, rank=2)
        try:
            with pytest.raises(TimeoutError):
                pipe.warm_join(
                    _FakeManager(block=gate), template={}, timeout=0.05
                )
        finally:
            gate.set()

    def test_load_error_propagates(self):
        pipe = EvacuationPipeline(store=None, rank=2)
        with pytest.raises(ValueError):
            pipe.warm_join(
                _FakeManager(error=ValueError("no candidates")),
                template={}, timeout=5.0,
            )


# ---- satellite 4: merged trace renders the evacuation span ------------------


def _rec(event, mono_ns, rank, **fields):
    return {"event": event, "mono_ns": mono_ns, "rank": rank, **fields}


class TestEvacuationTrace:
    def test_risk_cross_to_join_renders_one_span(self):
        out = trace.to_chrome_trace([
            _rec("evac.risk_cross", 1_000, 0, victim=1, risk=0.82,
                 episode="ep9"),
            _rec("evac.ckpt_ahead", 2_000, 0, victim=1, episode="ep9"),
            _rec("evac.promote", 3_000, 0, victim=1, spare="h:9",
                 episode="ep9"),
            _rec("evac.join", 9_000, 0, victim=1, source="peer_memory",
                 bytes=4096, dur_ms=1.5, episode="ep9"),
        ])["traceEvents"]
        spans = [e for e in out if e.get("ph") == "X"]
        assert len(spans) == 1
        span = spans[0]
        assert span["name"] == "evacuation" and span["cat"] == "evac"
        assert span["dur"] == pytest.approx(8.0)
        assert span["args"]["source"] == "peer_memory"

    def test_merged_dump_renders_evacuation_span(self, tmp_path):
        path = tmp_path / "flight.jsonl"
        with open(path, "w") as f:
            for rec in [
                {"event": "_flight_meta", "mono_ns": 0, "host": "h0",
                 "rank": 0},
                _rec("evac.risk_cross", 5_000, 0, victim=1, risk=0.9,
                     episode="ep2"),
                _rec("evac.join", 25_000, 0, victim=1, source="peer_memory",
                     bytes=128, dur_ms=0.02, episode="ep2"),
            ]:
                f.write(json.dumps(rec) + "\n")
        merged = trace.to_chrome_trace(
            trace.load_aligned([str(path)], warn=False)
        )
        names = [
            e["name"] for e in merged["traceEvents"] if e.get("ph") == "X"
        ]
        assert "evacuation" in names
