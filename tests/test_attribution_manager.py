"""Attribution service lifecycle (reference attribution_manager.py:47-140):
the launcher spawns/monitors attrsvc, resolves endpoints via the store, and
health-checks before the restart gate consults it."""

import time

import pytest

from tpu_resiliency.fault_tolerance.attribution_manager import (
    ENDPOINT_KEY,
    AttributionManager,
)
from tpu_resiliency.store import StoreClient


@pytest.fixture
def store(store_server):
    c = StoreClient("127.0.0.1", store_server.port, timeout=10.0)
    yield c
    c.close()


def test_spawn_publishes_endpoint_and_serves(store, tmp_path):
    mgr = AttributionManager(mode="spawn", store=store)
    mgr.start()
    try:
        url = store.try_get(ENDPOINT_KEY)
        assert url, "endpoint not published"
        assert mgr.healthy()
        # the gate path: POST a cycle log tail, get a verdict dict
        log_path = tmp_path / "cycle_0.log"
        log_path.write_text(
            "[r0] step 12 loss=2.1\n"
            "[r1] RuntimeError: Resource exhausted: Out of memory while "
            "trying to allocate 9663676416 bytes\n"
        )
        verdict = mgr.analyze_log(str(log_path))
        assert verdict is not None
        assert "category" in verdict and "should_resume" in verdict
    finally:
        mgr.stop()


def test_service_restarted_after_death(store):
    mgr = AttributionManager(mode="spawn", store=store)
    mgr.start()
    try:
        assert mgr.healthy()
        mgr._proc.kill()
        mgr._proc.wait(timeout=10)
        mgr.tick()  # monitor loop notices and respawns
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not mgr.healthy():
            time.sleep(0.2)
        assert mgr.healthy(), "service not restarted"
        assert mgr._restarts == 1
    finally:
        mgr.stop()


def test_external_mode_publishes_configured_url(store):
    mgr = AttributionManager(
        mode="external", store=store, url="http://10.0.0.9:8950"
    )
    mgr.start()
    assert store.get(ENDPOINT_KEY) == b"http://10.0.0.9:8950"
    # unreachable -> unhealthy -> gate falls back inline
    assert not mgr.healthy()
    assert mgr.analyze_log("/nonexistent") is None


def test_resolve_from_store_without_local_url(store):
    store.set(ENDPOINT_KEY, "http://10.1.2.3:1234")
    mgr = AttributionManager(mode="inline", store=store)
    assert mgr.resolve() == "http://10.1.2.3:1234"


def test_launcher_gate_via_service_stops_unsurvivable_failure(tmp_path):
    """E2E: enable_attribution_gate + attribution_service_mode=spawn — the
    launcher spawns attrsvc, the gate consults it over HTTP, and an OOM
    (non-survivable) failure STOPS the job instead of burning restarts."""
    from tests.test_launcher import run_launcher

    proc, ckpt = run_launcher(
        tmp_path,
        extra_env={
            "TOY_FAIL": "0:1:5",
            "TOY_FAIL_MSG": (
                "RuntimeError: Out of memory while trying to allocate "
                "96636764160 bytes"
            ),
            "TPURX_FT_ENABLE_ATTRIBUTION_GATE": "1",
            "TPURX_FT_ATTRIBUTION_SERVICE_MODE": "spawn",
        },
        iters=12,
        expect_rc=1,
        timeout=120,
    )
    err = proc.stderr
    assert "attribution (service)" in err, err[-3000:]
    assert "not survivable by restart" in err, err[-3000:]
    # no second cycle started
    assert "cycle=1 starting" not in proc.stdout
