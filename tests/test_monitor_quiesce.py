"""Deterministic absorption of late async restart raises (VERDICT r4 weak #4).

The old drain was ``time.sleep(0.05)`` — a timed race: a
``PyThreadState_SetAsyncExc`` scheduled just before ``mark_caught`` could be
delivered *after* the sleep, firing inside finalize/health-check/barrier and
escaping the restart loop.  The replacement is a handshake
(``MonitorThread.quiesce_raises``): check-and-raise is atomic with
``mark_caught`` under a lock, and the single-slot pending exception is
cancelled with ``PyThreadState_SetAsyncExc(tid, NULL)`` from the monitored
thread, absorbing any delivery that slips a bytecode boundary.

Reference semantics being matched: ``inprocess/monitor_thread.py:90-110``
(reraise_if_unraisable — the reference also re-raises until acknowledged).
"""

import threading
import time

import pytest

from tpu_resiliency.inprocess import monitor_thread as mt_mod
from tpu_resiliency.inprocess.attribution import (
    Interruption,
    InterruptionRecord,
)
from tpu_resiliency.inprocess.exceptions import RankShouldRestart
from tpu_resiliency.inprocess.monitor_thread import (
    MonitorThread,
    async_raise,
    quiesce_with_retry,
)
from tpu_resiliency.inprocess.store_ops import InprocStore
from tpu_resiliency.store import StoreServer
from tpu_resiliency.store.client import StoreClient


@pytest.fixture()
def ops():
    srv = StoreServer(host="127.0.0.1", port=0).start_in_thread()
    client = StoreClient("127.0.0.1", srv.port)
    yield InprocStore(client, "quiesce-test")
    client.close()
    srv.stop()


def _busy_bytecode(seconds: float) -> None:
    """Pure-Python busy loop: every iteration is a bytecode boundary, so any
    pending async exception WILL be delivered here if one exists."""
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        sum(range(50))


_quiesce = quiesce_with_retry  # production's absorbing call-site wrapper


def test_no_reraise_escapes_after_quiesce(ops):
    """Hammer the real re-raise loop: catch the first raise, quiesce, then
    run bytecode for longer than the 0.5s re-raise interval.  With the old
    timed drain the second scheduled raise escaped; the handshake makes the
    window zero."""
    mon = MonitorThread(
        ops, 0, threading.get_ident(), last_call_wait=0.0, poll_interval=0.05
    )
    mon.start()
    try:
        caught = False
        try:
            # the record write sits INSIDE the try: on a loaded 1-core host
            # the monitor can complete its whole trip while this thread is
            # still parked in the append's syscall, landing the raise on
            # the append's own return bytecode
            ops.record_interruption(
                0,
                InterruptionRecord(
                    rank=0, interruption=Interruption.EXCEPTION, message="inj"
                ),
            )
            _busy_bytecode(5.0)
        except RankShouldRestart:
            caught = True
        assert caught, "monitor never raised"
        # restart path: quiesce, then a "finalize" longer than the re-raise
        # interval — nothing may escape it
        _quiesce(mon)
        _busy_bytecode(1.2)
    finally:
        mon.stop()


def test_quiesce_cancels_undelivered_raise(ops):
    """Adversarial schedule: a raise lands in the async-exc slot from a
    helper thread; wherever the interpreter delivers it, after
    ``quiesce_raises`` returns the slot is empty and nothing fires."""
    mon = MonitorThread(ops, 0, threading.get_ident())  # never started
    main = threading.get_ident()
    t = threading.Thread(
        target=lambda: async_raise(main, RankShouldRestart), daemon=True
    )
    try:
        t.start()
        t.join()
    except RankShouldRestart:
        pass  # delivered before quiesce — the easy case
    _quiesce(mon)  # absorbs/cancels the hard case
    try:
        _busy_bytecode(0.6)
    except RankShouldRestart:
        pytest.fail("async raise escaped after quiesce completed")
    finally:
        mon._stop.set()


def test_quiesce_requires_monitored_thread(ops):
    mon = MonitorThread(ops, 0, threading.get_ident())
    err = {}

    def other():
        try:
            mon.quiesce_raises()
        except RuntimeError as exc:
            err["e"] = exc

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert "e" in err
    mon._stop.set()


class _LateRaisingMonitor(MonitorThread):
    """Adversary: after the normal raise loop ends, KEEP attempting raises
    through the real locked path until the wrapper stops us — attempts land
    throughout the restart path (quiesce, stop-join, finalize).  This proves
    the protocol (not a bypass of it) keeps the restart path safe: every
    attempt finds ``_caught`` set and schedules nothing."""

    attempted = threading.Event()

    def _run(self):
        super()._run()
        while not self._stop.is_set():
            with self._raise_lock:
                type(self).attempted.set()
                if not self._caught.is_set():
                    async_raise(self.main_tid, RankShouldRestart)
            time.sleep(0.005)


def test_restart_path_survives_late_raise(ops, monkeypatch):
    """E2e: a fault restarts the wrapped fn; the hooked monitor tries to
    raise again during finalize; the restart completes and iteration 1
    returns normally (VERDICT r4 'do this' #5)."""
    from tpu_resiliency.inprocess import wrap as wrap_mod
    from tpu_resiliency.inprocess import Wrapper

    _LateRaisingMonitor.attempted.clear()
    monkeypatch.setattr(wrap_mod, "MonitorThread", _LateRaisingMonitor)

    def finalize(_state):
        # busy bytecode: if a late raise escaped quiesce it fires here, in
        # the restart path, and the wrapper (pre-fix) would crash
        _busy_bytecode(0.3)

    def train(call_wrapper=None):
        if call_wrapper.iteration == 0:
            raise ValueError("injected fault")
        return "recovered"

    wrapper = Wrapper(
        store_factory=lambda: ops.store.clone(),
        group="late-raise-e2e",
        finalize=finalize,
        soft_timeout=3600.0,
        hard_timeout=7200.0,
        enable_monitor_process=False,
        enable_sibling_monitor=False,
        last_call_wait=0.0,
    )
    assert wrapper(train)() == "recovered"
    assert _LateRaisingMonitor.attempted.is_set(), (
        "adversary never ran — test lost its teeth"
    )
