"""Repo-hygiene invariants.

The native helpers (``native/*.so``, ``native/tpurx-store-server``) are
built on first use by ``tpu_resiliency/utils/native.py`` — compiled
artifacts must never be tracked in git, where they are unreviewable and go
stale against their sources (VERDICT r4 weak #5).
"""

import os
import subprocess

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _tracked_files():
    try:
        out = subprocess.run(
            ["git", "ls-files", "-z"], cwd=REPO, capture_output=True,
            text=True, timeout=30, check=True,
        )
    except (OSError, subprocess.SubprocessError):
        pytest.skip("not a git checkout")
    return [p for p in out.stdout.split("\0") if p]


def test_no_compiled_artifacts_tracked_in_git():
    offenders = []
    for rel in _tracked_files():
        base = os.path.basename(rel)
        if base.endswith((".so", ".o", ".a", ".pyc", ".dylib")):
            offenders.append(rel)
            continue
        path = os.path.join(REPO, rel)
        try:
            with open(path, "rb") as f:
                magic = f.read(4)
        except OSError:
            continue
        if magic == b"\x7fELF":
            offenders.append(rel)
    assert not offenders, (
        f"compiled artifacts tracked in git (build-on-first-use makes them "
        f"redundant; see utils/native.py): {offenders}"
    )


def test_native_build_outputs_are_gitignored():
    """A fresh build must not dirty the tree: every Makefile output under
    native/ is covered by .gitignore."""
    for artifact in (
        "native/tpurx-store-server",
        "native/libtpurx-pending.so",
        "native/libtpurx-opring.so",
        "native/libtpurx-beat.so",
    ):
        rc = subprocess.run(
            ["git", "check-ignore", "-q", artifact], cwd=REPO, timeout=30,
        ).returncode
        assert rc == 0, f"{artifact} is not gitignored"
