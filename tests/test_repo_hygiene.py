"""Repo-hygiene invariants.

The native helpers (``native/*.so``, ``native/tpurx-store-server``) are
built on first use by ``tpu_resiliency/utils/native.py`` — compiled
artifacts must never be tracked in git, where they are unreviewable and go
stale against their sources (VERDICT r4 weak #5).

Library output discipline: structured logging only — a bare ``print()`` in
a library module bypasses rank prefixes, the log funnel, and level control.
CLI entry points (argparse mains that talk to a terminal) are allowlisted.

Telemetry discipline: every metric name an instrumentation call site
references must be declared exactly once with a valid OpenMetrics name, and
importing the defining module must actually register it.
"""

import ast
import importlib
import os
import subprocess

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
PKG = os.path.join(REPO, "tpu_resiliency")

# CLI entry points: argparse mains whose stdout IS the interface
PRINT_ALLOWLIST = {
    "tpu_resiliency/straggler/inspect.py",
    "tpu_resiliency/utils/shm_janitor.py",
    "tpu_resiliency/health/device.py",
    "tpu_resiliency/fault_tolerance/per_cycle_logs.py",
    "tpu_resiliency/telemetry/trace.py",
}


def _tracked_files():
    try:
        out = subprocess.run(
            ["git", "ls-files", "-z"], cwd=REPO, capture_output=True,
            text=True, timeout=30, check=True,
        )
    except (OSError, subprocess.SubprocessError):
        pytest.skip("not a git checkout")
    return [p for p in out.stdout.split("\0") if p]


def test_no_compiled_artifacts_tracked_in_git():
    offenders = []
    for rel in _tracked_files():
        base = os.path.basename(rel)
        if base.endswith((".so", ".o", ".a", ".pyc", ".dylib")):
            offenders.append(rel)
            continue
        path = os.path.join(REPO, rel)
        try:
            with open(path, "rb") as f:
                magic = f.read(4)
        except OSError:
            continue
        if magic == b"\x7fELF":
            offenders.append(rel)
    assert not offenders, (
        f"compiled artifacts tracked in git (build-on-first-use makes them "
        f"redundant; see utils/native.py): {offenders}"
    )


def test_native_build_outputs_are_gitignored():
    """A fresh build must not dirty the tree: every Makefile output under
    native/ is covered by .gitignore."""
    for artifact in (
        "native/tpurx-store-server",
        "native/libtpurx-pending.so",
        "native/libtpurx-opring.so",
        "native/libtpurx-beat.so",
    ):
        rc = subprocess.run(
            ["git", "check-ignore", "-q", artifact], cwd=REPO, timeout=30,
        ).returncode
        assert rc == 0, f"{artifact} is not gitignored"


def _library_sources():
    for root, _dirs, files in os.walk(PKG):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, REPO).replace(os.sep, "/")
            yield rel, path


def test_no_bare_print_in_library_modules():
    """AST-based (strings and comments can't false-positive): any
    ``print(...)`` call outside the CLI allowlist is an offender."""
    offenders = []
    for rel, path in _library_sources():
        if rel in PRINT_ALLOWLIST:
            continue
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        f"bare print() in library modules (use utils.logging.get_logger, or "
        f"add a CLI entry point to PRINT_ALLOWLIST): {offenders}"
    )


def test_no_raw_binary_reads_in_checkpointing_modules():
    """Checkpoint payload bytes must only enter the process through the
    verifying readers (``checkpointing/integrity.py``): any
    ``open(..., "rb")`` elsewhere under ``tpu_resiliency/checkpointing/``
    is a trust-boundary bypass — the exact unguarded-read pattern this
    repo's corrupt-shard quarantine exists to eliminate.  The ban also
    covers the positioned-read primitives the streaming chunk reader is
    built on (``os.read`` / ``os.pread`` / ``os.preadv`` / ``os.readv``):
    the parallel restore engine must take its bytes from
    ``integrity.ChunkReader``, never its own descriptor reads.  AST-based
    like the bare-print ban (strings/comments can't false-positive)."""
    allowlist = {"tpu_resiliency/checkpointing/integrity.py"}
    os_read_calls = {"read", "pread", "preadv", "readv"}
    offenders = []
    for rel, path in _library_sources():
        if not rel.startswith("tpu_resiliency/checkpointing/"):
            continue
        if rel in allowlist:
            continue
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in os_read_calls
                and isinstance(func.value, ast.Name)
                and func.value.id == "os"
            ):
                offenders.append(f"{rel}:{node.lineno} (os.{func.attr})")
                continue
            if not (isinstance(func, ast.Name) and func.id == "open"):
                continue
            mode = None
            if len(node.args) >= 2:
                mode = node.args[1]
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if (
                isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and "r" in mode.value
                and "b" in mode.value
            ):
                offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        f"raw binary reads of checkpoint data outside the verifying reader "
        f"(use integrity.read_verified_blob / read_verified_shard / "
        f"ChunkReader): {offenders}"
    )


_STAMP_TOKENS = ("stamp", "beat", "timestamp", "heartbeat")


def _target_names(node) -> list:
    """Flatten an assignment target into its name/attr identifier chain."""
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.append(sub.attr)
    return out


def _calls_wall_clock(expr) -> bool:
    """True when the expression contains a ``time.time()`` /
    ``time.time_ns()`` call."""
    for sub in ast.walk(expr):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr in ("time", "time_ns")
            and isinstance(sub.func.value, ast.Name)
            and sub.func.value.id == "time"
        ):
            return True
    return False


def test_no_raw_wall_clock_stamps_outside_quorum():
    """Liveness stamps must derive from ``ops/quorum.py``'s clock helpers
    (``now_stamp_ns`` / ``wall_time_s``): a raw ``time.time()``-derived
    stamp re-decides the epoch/fold/clock-domain contract locally, and one
    site drifting (ms vs ns, wall vs monotonic, unfolded epoch) breaks the
    wrap-safe age math every detector shares — the exact bug class the
    ns-scale stamp rebuild exists to close.  AST-based like the other
    bans: any assignment whose target names a stamp (``*stamp*``,
    ``*beat*``, ``*timestamp*``, ``*heartbeat*``) from a
    ``time.time()``/``time.time_ns()``-containing expression is an
    offender outside the allowlist."""
    allowlist = {
        # the single home of the stamp/clock contract
        "tpu_resiliency/ops/quorum.py",
    }
    offenders = []
    for rel, path in _library_sources():
        if rel in allowlist:
            continue
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        for node in ast.walk(tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                names = []
                for t in targets:
                    names.extend(_target_names(t))
                if not any(
                    tok in name.lower() for name in names
                    for tok in _STAMP_TOKENS
                ):
                    continue
                if node.value is not None and _calls_wall_clock(node.value):
                    offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        f"raw time.time()-derived stamps outside ops/quorum.py (use "
        f"quorum.now_stamp_ns / quorum.wall_time_s so the epoch and "
        f"clock-domain contract has one home): {offenders}"
    )


def _range_references_world_size(call: ast.Call) -> bool:
    """True when ``call`` is ``range(...)`` with an argument mentioning
    ``world_size`` (a Name, an Attribute like ``self.world_size``, or any
    expression containing one)."""
    if not (isinstance(call.func, ast.Name) and call.func.id == "range"):
        return False
    for arg in call.args:
        for node in ast.walk(arg):
            if isinstance(node, ast.Name) and node.id == "world_size":
                return True
            if isinstance(node, ast.Attribute) and node.attr == "world_size":
                return True
    return False


def test_no_flat_all_ranks_gathers_outside_tree_helper():
    """Cross-rank gather rounds must route through the reduction tree
    (``store/tree.py``): a direct all-ranks-to-one gather — reading one
    store key per rank of the world — makes rank 0 (and the shard owning
    the round's keys) an O(N) hotspot, the exact pattern the sharded
    control plane + hierarchical aggregation refactor removed.  AST-based
    like the rb-read ban; two shapes are banned outside the allowlist:

    - ``store.multi_get([...for r in range(world_size)])`` (and any
      comprehension over ``range(*world_size*)`` passed to ``multi_get``);
    - ``store.get/try_get`` calls inside a ``for ... in range(*world_size*)``
      loop.
    """
    allowlist = {
        # the sanctioned reduction-tree helper itself
        "tpu_resiliency/store/tree.py",
        # post-mortem reads of possibly-dead ranks: no collective is
        # possible, the observer must poll whatever keys exist
        "tpu_resiliency/attribution/trace_analyzer.py",
        # single-process emulation moving BULK blob bytes (not control
        # metadata): funneling payloads through a tree root would
        # centralize the very bytes replication spreads out
        "tpu_resiliency/checkpointing/local/ici_replication.py",
    }
    store_read_attrs = {"multi_get", "get", "try_get"}
    offenders = []
    for rel, path in _library_sources():
        if rel in allowlist:
            continue
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        for node in ast.walk(tree):
            # shape 1: multi_get(<comprehension over range(world_size)>)
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "multi_get"
            ):
                for arg in node.args:
                    comps = [
                        c
                        for sub in ast.walk(arg)
                        if isinstance(sub, (ast.ListComp, ast.GeneratorExp, ast.SetComp))
                        for c in sub.generators
                    ]
                    if any(
                        isinstance(c.iter, ast.Call)
                        and _range_references_world_size(c.iter)
                        for c in comps
                    ):
                        offenders.append(f"{rel}:{node.lineno} (multi_get)")
            # shape 2: store reads inside `for r in range(world_size):`
            if (
                isinstance(node, ast.For)
                and isinstance(node.iter, ast.Call)
                and _range_references_world_size(node.iter)
            ):
                for sub in ast.walk(node):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in store_read_attrs
                        and isinstance(sub.func.value, (ast.Name, ast.Attribute))
                        and "store" in ast.dump(sub.func.value).lower()
                    ):
                        offenders.append(
                            f"{rel}:{sub.lineno} ({sub.func.attr} in "
                            f"range(world_size) loop)"
                        )
    assert not offenders, (
        f"flat all-ranks-to-one gather outside store/tree.py (route the "
        f"round through tree_gather — rank-0 inbound must stay O(fanout)): "
        f"{offenders}"
    )


def _declared_metric_names():
    """(name, rel, lineno) for every registry-constructor call with a
    literal first argument anywhere in the package."""
    ctors = {"counter", "gauge", "histogram"}
    out = []
    for rel, path in _library_sources():
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = None
            if isinstance(func, ast.Name) and func.id in ctors:
                name = func.id
            elif isinstance(func, ast.Attribute) and func.attr in ctors:
                name = func.attr
            if name is None or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                if first.value.startswith("tpurx_"):
                    out.append((first.value, rel, node.lineno))
    return out


def test_metric_names_valid_and_declared_exactly_once():
    from tpu_resiliency.telemetry import valid_metric_name

    declared = _declared_metric_names()
    assert declared, "no metric declarations found — scanner broken?"
    seen = {}
    for name, rel, lineno in declared:
        assert valid_metric_name(name), f"invalid OpenMetrics name {name!r} at {rel}:{lineno}"
        seen.setdefault(name, []).append(f"{rel}:{lineno}")
    dupes = {n: sites for n, sites in seen.items() if len(sites) > 1}
    assert not dupes, (
        f"metric names declared at more than one call site (move the "
        f"declaration to one module and import the handle): {dupes}"
    )


def test_declared_metrics_register_on_import():
    """Importing each declaring module must land its names in the default
    registry — a typo'd registration (or a module-local registry) would
    silently drop the series from every exporter."""
    from tpu_resiliency.telemetry import get_registry

    declared = _declared_metric_names()
    for _name, rel, _lineno in declared:
        mod = rel[: -len(".py")].replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        importlib.import_module(mod)
    registered = set(get_registry().names())
    missing = {n for n, _r, _l in declared} - registered
    assert not missing, f"declared but never registered: {sorted(missing)}"
