"""Repo-hygiene invariants.

The native helpers (``native/*.so``, ``native/tpurx-store-server``) are
built on first use by ``tpu_resiliency/utils/native.py`` — compiled
artifacts must never be tracked in git, where they are unreviewable and go
stale against their sources (VERDICT r4 weak #5).

The four AST bans that used to live here (bare prints, raw rb-reads, raw
wall-clock stamps, flat gathers) are now rules TPURX001–TPURX004 of the
``tpurx_lint`` framework; the tests below are thin shims that keep the
historical test names while delegating to the framework (suppressions and
the reviewed baseline apply — see docs/lint.md).  The full all-rule gate is
``tests/test_tpurx_lint.py::TestRepoGate``.

Telemetry discipline: every metric name an instrumentation call site
references must be declared exactly once with a valid OpenMetrics name, and
importing the defining module must actually register it.
"""

import ast
import importlib
import os
import re
import subprocess

import pytest

from tpurx_lint import run_lint

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
PKG = os.path.join(REPO, "tpu_resiliency")

LINT_PATHS = ["tpu_resiliency", "tests", "benchmarks", "tpurx_lint"]


def _tracked_files():
    try:
        out = subprocess.run(
            ["git", "ls-files", "-z"], cwd=REPO, capture_output=True,
            text=True, timeout=30, check=True,
        )
    except (OSError, subprocess.SubprocessError):
        pytest.skip("not a git checkout")
    return [p for p in out.stdout.split("\0") if p]


def test_no_compiled_artifacts_tracked_in_git():
    offenders = []
    for rel in _tracked_files():
        base = os.path.basename(rel)
        if base.endswith((".so", ".o", ".a", ".pyc", ".dylib")):
            offenders.append(rel)
            continue
        path = os.path.join(REPO, rel)
        try:
            with open(path, "rb") as f:
                magic = f.read(4)
        except OSError:
            continue
        if magic == b"\x7fELF":
            offenders.append(rel)
    assert not offenders, (
        f"compiled artifacts tracked in git (build-on-first-use makes them "
        f"redundant; see utils/native.py): {offenders}"
    )


def test_native_build_outputs_are_gitignored():
    """A fresh build must not dirty the tree: every Makefile output under
    native/ is covered by .gitignore."""
    for artifact in (
        "native/tpurx-store-server",
        "native/libtpurx-pending.so",
        "native/libtpurx-opring.so",
        "native/libtpurx-beat.so",
    ):
        rc = subprocess.run(
            ["git", "check-ignore", "-q", artifact], cwd=REPO, timeout=30,
        ).returncode
        assert rc == 0, f"{artifact} is not gitignored"


# -- framework-backed shims (rule IDs TPURX001-004, see docs/lint.md) --------


def _assert_rule_clean(rule_id: str):
    result = run_lint(paths=LINT_PATHS, root=REPO, rule_ids=[rule_id])
    assert not result.parse_errors, result.parse_errors
    assert not result.findings, "\n".join(
        f"{f.location()}: {f.rule} {f.message}" for f in result.findings
    )


def test_no_bare_print_in_library_modules():
    """tpurx-lint TPURX001 (bare-print)."""
    _assert_rule_clean("TPURX001")


def test_no_raw_binary_reads_in_checkpointing_modules():
    """tpurx-lint TPURX002 (raw-ckpt-read)."""
    _assert_rule_clean("TPURX002")


def test_no_raw_wall_clock_stamps_outside_quorum():
    """tpurx-lint TPURX003 (raw-wall-clock-stamp)."""
    _assert_rule_clean("TPURX003")


def test_no_flat_all_ranks_gathers_outside_tree_helper():
    """tpurx-lint TPURX004 (flat-gather)."""
    _assert_rule_clean("TPURX004")


def test_deep_resiliency_rules_clean():
    """tpurx-lint TPURX005-010 (deadline / abort-path / retry / thread /
    exception / env-registry discipline) — zero non-baselined findings."""
    result = run_lint(paths=LINT_PATHS, root=REPO, rule_ids=[
        "TPURX005", "TPURX006", "TPURX007", "TPURX008", "TPURX009", "TPURX010",
    ])
    assert not result.findings, "\n".join(
        f"{f.location()}: {f.rule} {f.message}" for f in result.findings
    )


# -- telemetry discipline ----------------------------------------------------


def _library_sources():
    for root, _dirs, files in os.walk(PKG):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, REPO).replace(os.sep, "/")
            yield rel, path


def _declared_metric_names():
    """(name, rel, lineno) for every registry-constructor call with a
    literal first argument anywhere in the package."""
    ctors = {"counter", "gauge", "histogram"}
    out = []
    for rel, path in _library_sources():
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = None
            if isinstance(func, ast.Name) and func.id in ctors:
                name = func.id
            elif isinstance(func, ast.Attribute) and func.attr in ctors:
                name = func.attr
            if name is None or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                if first.value.startswith("tpurx_"):
                    out.append((first.value, rel, node.lineno))
    return out


def test_metric_names_valid_and_declared_exactly_once():
    from tpu_resiliency.telemetry import valid_metric_name

    declared = _declared_metric_names()
    assert declared, "no metric declarations found — scanner broken?"
    seen = {}
    for name, rel, lineno in declared:
        assert valid_metric_name(name), f"invalid OpenMetrics name {name!r} at {rel}:{lineno}"
        seen.setdefault(name, []).append(f"{rel}:{lineno}")
    dupes = {n: sites for n, sites in seen.items() if len(sites) > 1}
    assert not dupes, (
        f"metric names declared at more than one call site (move the "
        f"declaration to one module and import the handle): {dupes}"
    )


def test_declared_metrics_register_on_import():
    """Importing each declaring module must land its names in the default
    registry — a typo'd registration (or a module-local registry) would
    silently drop the series from every exporter."""
    from tpu_resiliency.telemetry import get_registry

    declared = _declared_metric_names()
    for _name, rel, _lineno in declared:
        mod = rel[: -len(".py")].replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        importlib.import_module(mod)
    registered = set(get_registry().names())
    missing = {n for n, _r, _l in declared} - registered
    assert not missing, f"declared but never registered: {sorted(missing)}"


def _declared_flight_events():
    """(name, fields, rel, lineno) for every ``declare_event`` call with a
    literal first argument anywhere in the package — the flight-recorder
    analog of :func:`_declared_metric_names`."""
    out = []
    for rel, path in _library_sources():
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name):
                ctor = func.id
            elif isinstance(func, ast.Attribute):
                ctor = func.attr
            else:
                continue
            if ctor != "declare_event" or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                fields = tuple(
                    a.value for a in node.args[1:]
                    if isinstance(a, ast.Constant) and isinstance(a.value, str)
                )
                out.append((first.value, fields, rel, node.lineno))
    return out


_FLIGHT_EVENT_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")


def test_flight_event_names_valid_and_declared_exactly_once():
    """Flight-event names follow the metric-name discipline: dotted
    lowercase (``subsystem.event`` — the prefix becomes the trace
    category), declared ONCE at module scope with a literal string, and
    record sites import the handle."""
    declared = _declared_flight_events()
    assert declared, "no declare_event declarations found — scanner broken?"
    seen = {}
    for name, fields, rel, lineno in declared:
        assert _FLIGHT_EVENT_RE.match(name), (
            f"flight event {name!r} at {rel}:{lineno} is not dotted "
            f"lowercase (subsystem.event)"
        )
        for field in fields:
            assert re.match(r"^[a-z][a-z0-9_]*$", field), (
                f"flight event {name!r} field {field!r} at {rel}:{lineno} "
                f"is not a lowercase identifier"
            )
        seen.setdefault(name, []).append(f"{rel}:{lineno}")
    dupes = {n: sites for n, sites in seen.items() if len(sites) > 1}
    assert not dupes, (
        f"flight event names declared at more than one call site (declare "
        f"once at module scope, import the handle): {dupes}"
    )


def test_declared_flight_events_register_on_import():
    """Importing each declaring module must land its event names in the
    flight module's registry — a never-imported declaration would dump
    records with positional ``argN`` keys instead of field names."""
    from tpu_resiliency.telemetry import flight

    declared = _declared_flight_events()
    for _name, _fields, rel, _lineno in declared:
        mod = rel[: -len(".py")].replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        importlib.import_module(mod)
    registered = set(flight.event_names())
    missing = {n for n, _f, _r, _l in declared} - registered
    assert not missing, f"declared but never registered: {sorted(missing)}"


def test_env_doc_is_fresh():
    """docs/configuration.md must match the knob registry (regenerate with
    ``python -m tpu_resiliency.utils.env --write``)."""
    from tpu_resiliency.utils import env

    with open(os.path.join(REPO, "docs", "configuration.md")) as f:
        on_disk = f.read()
    assert on_disk == env.render_markdown(), (
        "docs/configuration.md is stale — run "
        "`python -m tpu_resiliency.utils.env --write`"
    )
