"""Barrier rendezvous tests (reference analog: tests/fault_tolerance/unit/test_barrier_rendezvous.py).

Nodes are threads sharing a real store server — same store protocol a
multi-host deployment uses.
"""

import threading
import time

import pytest

from tpu_resiliency.fault_tolerance.rendezvous import (
    NodeDesc,
    NodeRole,
    RendezvousClosedError,
    RendezvousError,
    RendezvousHost,
    RendezvousJoiner,
    RendezvousTimeout,
    assign_group_ranks,
)
from tpu_resiliency.store import StoreClient


def _node(i, slots=2, slice_key="", prev=None, excluded=False):
    return NodeDesc(
        node_id=f"node{i}", hostname=f"h{i}", slots=slots, slice_key=slice_key,
        prev_group_rank=prev, arrival=i, excluded=excluded,
    )


class TestAssignGroupRanks:
    def test_basic(self):
        nodes = [_node(0), _node(1), _node(2)]
        out = assign_group_ranks(nodes, min_nodes=2, max_nodes=None)
        ranks = {nid: a["group_rank"] for nid, a in out.items()}
        assert sorted(ranks.values()) == [0, 1, 2]

    def test_spares_beyond_max(self):
        nodes = [_node(i) for i in range(5)]
        out = assign_group_ranks(nodes, min_nodes=2, max_nodes=3)
        roles = [a["role"] for a in out.values()]
        assert roles.count(NodeRole.PARTICIPANT.value) == 3
        assert roles.count(NodeRole.STANDBY.value) == 2

    def test_rank_stability(self):
        # node2 had rank 0 before: keeps it; new nodes fill after
        nodes = [_node(0), _node(1, prev=1), _node(2, prev=0)]
        out = assign_group_ranks(nodes, min_nodes=1, max_nodes=None)
        assert out["node2"]["group_rank"] == 0
        assert out["node1"]["group_rank"] == 1
        assert out["node0"]["group_rank"] == 2

    def test_excluded_nodes_skipped(self):
        nodes = [_node(0, excluded=True), _node(1), _node(2)]
        out = assign_group_ranks(nodes, min_nodes=2, max_nodes=2)
        assert out["node0"]["role"] == NodeRole.EXCLUDED.value
        assert out["node0"]["group_rank"] is None
        assert out["node1"]["group_rank"] is not None

    def test_min_nodes_violated(self):
        with pytest.raises(Exception):
            assign_group_ranks([_node(0, excluded=True)], min_nodes=1, max_nodes=None)

    def test_slices_kept_whole(self):
        # two slices of 2 plus a loner; cap 2 -> take one whole slice, not a mix
        nodes = [
            _node(0, slice_key="sliceA"), _node(1, slice_key="sliceA"),
            _node(2, slice_key="sliceB"), _node(3, slice_key="sliceB"),
        ]
        out = assign_group_ranks(nodes, min_nodes=2, max_nodes=2)
        chosen = {nid for nid, a in out.items() if a["group_rank"] is not None}
        assert chosen in ({"node0", "node1"}, {"node2", "node3"})

    def test_heterogeneous_slots_rejected(self):
        with pytest.raises(Exception):
            assign_group_ranks([_node(0, slots=2), _node(1, slots=4)], 1, None)


@pytest.fixture
def rdzv_store(store_server):
    def make():
        return StoreClient("127.0.0.1", store_server.port, timeout=20.0)

    yield make


def _run_join(store_factory, desc, results, timeout=20.0):
    joiner = RendezvousJoiner(store_factory(), desc, open_poll_interval=0.05)
    try:
        results[desc.node_id] = joiner.join(timeout=timeout)
    except Exception as exc:  # noqa: BLE001
        results[desc.node_id] = exc


def test_full_round(rdzv_store):
    host = RendezvousHost(rdzv_store(), min_nodes=3, max_nodes=3, settle_time=0.2)
    host.bootstrap()
    host.open_round()
    results = {}
    threads = [
        threading.Thread(
            target=_run_join, args=(rdzv_store, NodeDesc.create(f"n{i}", slots=4), results)
        )
        for i in range(3)
    ]
    for t in threads:
        t.start()
    host.close_round_when_ready(timeout=20.0)
    for t in threads:
        t.join(timeout=20.0)
    assert len(results) == 3
    ranks = sorted(r.group_rank for r in results.values())
    assert ranks == [0, 1, 2]
    for r in results.values():
        assert r.global_world_size == 12
        assert r.group_world_size == 3
        assert r.rank_offset == r.group_rank * 4
        assert r.role == NodeRole.PARTICIPANT


def test_excluded_rejoin_does_not_preempt_spare(rdzv_store):
    """Event-driven close + health-aware gate: an EXCLUDED node re-joining a
    fresh round milliseconds before the replacement spare must not satisfy
    the max-nodes gate — the close waits (within the settle window) and the
    spare still makes the round (r5 regression caught by the mid-cycle
    exclusion e2e, pinned here as a unit test)."""
    host = RendezvousHost(rdzv_store(), min_nodes=2, max_nodes=2,
                          settle_time=1.0)
    host.bootstrap()
    host.open_round()
    results = {}
    excluded = NodeDesc.create("bad", slots=1)
    excluded.excluded = True
    t_a = threading.Thread(
        target=_run_join, args=(rdzv_store, NodeDesc.create("good-a", slots=1), results)
    )
    t_bad = threading.Thread(target=_run_join, args=(rdzv_store, excluded, results))
    t_a.start()
    t_bad.start()
    time.sleep(0.3)  # both arrivals land; raw count already == max

    def late_spare():
        time.sleep(0.2)  # inside the settle window
        _run_join(rdzv_store, NodeDesc.create("good-b", slots=1), results)

    t_spare = threading.Thread(target=late_spare)
    t_spare.start()
    host.close_round_when_ready(timeout=20.0)
    for t in (t_a, t_bad, t_spare):
        t.join(timeout=20.0)
    assert results["good-a"].role == NodeRole.PARTICIPANT
    assert results["good-b"].role == NodeRole.PARTICIPANT
    assert isinstance(results["bad"], RendezvousClosedError)  # excluded


def test_all_unhealthy_closes_after_settle_and_fails_fast(rdzv_store):
    """No spare will ever come: once the settle window expires the round
    closes with the unhealthy arrivals and assignment raises the precise
    'not enough healthy nodes' error promptly (not the round timeout)."""
    host = RendezvousHost(rdzv_store(), min_nodes=1, max_nodes=1,
                          settle_time=0.3)
    host.bootstrap()
    host.open_round()
    results = {}
    bad = NodeDesc.create("only", slots=1)
    bad.excluded = True
    t = threading.Thread(target=_run_join, args=(rdzv_store, bad, results))
    t.start()
    t0 = time.monotonic()
    with pytest.raises(RendezvousError, match="not enough healthy"):
        host.close_round_when_ready(timeout=30.0)
    assert time.monotonic() - t0 < 10.0  # settle expiry, not round timeout
    host.shutdown("test over")
    t.join(timeout=10.0)


def test_hot_spare_promoted_on_restart(rdzv_store):
    """4 nodes, max 3: one becomes standby; when a participant dies and a new
    round opens, the spare is promoted with rank continuity for survivors."""
    host = RendezvousHost(rdzv_store(), min_nodes=3, max_nodes=3, settle_time=0.3)
    host.bootstrap()
    host.open_round()
    results = {}
    descs = {f"n{i}": NodeDesc.create(f"n{i}", slots=1) for i in range(4)}
    threads = [
        threading.Thread(target=_run_join, args=(rdzv_store, descs[f"n{i}"], results))
        for i in range(4)
    ]
    for t in threads:
        t.start()
    host.close_round_when_ready(timeout=20.0)
    # the spare's thread keeps waiting at the next open gate; 3 finish
    deadline = time.monotonic() + 10
    while sum(1 for r in results.values() if not isinstance(r, Exception)) < 3:
        assert time.monotonic() < deadline
        time.sleep(0.05)
    participant_ids = {nid for nid, r in results.items() if getattr(r, "group_rank", None) is not None}
    spare_id = set(descs) - participant_ids
    assert len(spare_id) == 1
    spare_id = spare_id.pop()

    # round 2: one participant (rank 2) died; survivors + spare rejoin
    dead = next(nid for nid in participant_ids if results[nid].group_rank == 2)
    survivors = participant_ids - {dead}
    host.open_round()
    results2 = {}
    threads2 = [
        threading.Thread(target=_run_join, args=(rdzv_store, descs[nid], results2))
        for nid in survivors
    ]
    for t in threads2:
        t.start()
    host.close_round_when_ready(timeout=20.0)
    for t in threads + threads2:
        t.join(timeout=20.0)
    # the spare (still in its first join() call) got promoted
    spare_result = results[spare_id]
    assert not isinstance(spare_result, Exception)
    assert spare_result.role == NodeRole.PARTICIPANT
    # survivors kept their previous ranks
    for nid in survivors:
        assert results2[nid].group_rank == results[nid].group_rank
    all_ranks = sorted(
        [results2[nid].group_rank for nid in survivors] + [spare_result.group_rank]
    )
    assert all_ranks == [0, 1, 2]
    assert spare_result.cycle == 1


def test_shutdown_releases_waiters(rdzv_store):
    host = RendezvousHost(rdzv_store(), min_nodes=2, settle_time=0.1)
    host.bootstrap()
    results = {}
    t = threading.Thread(
        target=_run_join, args=(rdzv_store, NodeDesc.create("w0"), results, 10.0)
    )
    t.start()
    time.sleep(0.3)
    host.shutdown("test over")
    t.join(timeout=10.0)
    assert isinstance(results["w0"], RendezvousClosedError)


def test_close_timeout_without_min_nodes(rdzv_store):
    host = RendezvousHost(rdzv_store(), min_nodes=2, settle_time=0.1)
    host.bootstrap()
    host.open_round()
    results = {}
    t = threading.Thread(
        target=_run_join, args=(rdzv_store, NodeDesc.create("only"), results, 5.0)
    )
    t.start()
    with pytest.raises(RendezvousTimeout):
        host.close_round_when_ready(timeout=1.0)
    t.join(timeout=10.0)


def test_unhealthy_node_does_not_join(rdzv_store):
    from tpu_resiliency.fault_tolerance.rendezvous import UnhealthyNodeError

    host = RendezvousHost(rdzv_store(), min_nodes=1, max_nodes=2, settle_time=0.3)
    host.bootstrap()
    host.open_round()

    def bad_health():
        raise UnhealthyNodeError("injected bad device")

    results = {}
    bad = RendezvousJoiner(rdzv_store(), NodeDesc.create("bad"), pre_join_health_check=bad_health)

    def run_bad():
        try:
            bad.join(timeout=5.0)
        except UnhealthyNodeError as e:
            results["bad"] = e

    threads = [
        threading.Thread(target=run_bad),
        threading.Thread(target=_run_join, args=(rdzv_store, NodeDesc.create("good"), results)),
    ]
    for t in threads:
        t.start()
    host.close_round_when_ready(timeout=10.0)
    for t in threads:
        t.join(timeout=10.0)
    assert isinstance(results["bad"], UnhealthyNodeError)
    assert results["good"].group_rank == 0
    assert results["good"].group_world_size == 1


def test_stale_writer_cannot_corrupt_new_round(rdzv_store):
    """Round fencing (reference ft_rendezvous_barrier.py:1954-1997): writes
    keyed to an old round are invisible to the new round's assignment."""
    host = RendezvousHost(rdzv_store(), min_nodes=1, max_nodes=2, settle_time=0.2)
    host.bootstrap()
    host.open_round()
    results = {}
    t = threading.Thread(
        target=_run_join, args=(rdzv_store, NodeDesc.create("good"), results)
    )
    t.start()
    host.close_round_when_ready(timeout=20.0)
    t.join(timeout=20.0)
    assert results["good"].round_num == 0

    # a stale incarnation writes into round 0's keys AFTER round 1 opens
    from tpu_resiliency.fault_tolerance.rendezvous import (
        k_join_count,
        k_node,
        request_restart,
    )

    store = rdzv_store()
    request_restart(store, "test")
    # host loop isn't running here; open manually
    host.open_round()
    stale = NodeDesc.create("zombie")
    store.add(k_join_count(0), 1)                       # old round's counter
    store.set(k_node(0, stale.node_id), stale.to_json())  # old round's slot
    # new round proceeds with only the good node; zombie's stale writes are
    # invisible because every key embeds the round number
    results2 = {}
    t2 = threading.Thread(
        target=_run_join, args=(rdzv_store, NodeDesc.create("good"), results2)
    )
    t2.start()
    host.close_round_when_ready(timeout=20.0)
    t2.join(timeout=20.0)
    r = results2["good"]
    assert r.round_num == 1
    assert r.participants == [r.participants[0]]  # exactly one participant
    assert "zombie" not in r.participants


def test_round_gc_reclaims_old_rounds(rdzv_store):
    """Crash-looping jobs must not grow the store: old rounds' keys are GCed."""
    store = rdzv_store()
    host = RendezvousHost(store, min_nodes=1, max_nodes=1, settle_time=0.05)
    host.bootstrap()
    host.open_round()
    for round_num in range(5):
        results = {}
        t = threading.Thread(
            target=_run_join, args=(rdzv_store, NodeDesc.create(f"n-{round_num}"), results)
        )
        t.start()
        host.close_round_when_ready(timeout=20.0)
        t.join(timeout=20.0)
        from tpu_resiliency.fault_tolerance.rendezvous import request_restart

        if round_num < 4:
            request_restart(store, "loop")
            host.open_round()
    # rounds older than current-2 are gone; recent rounds remain
    from tpu_resiliency.fault_tolerance.rendezvous import k_result

    gone = {k_result(0).encode(), k_result(1).encode()}
    assert not gone & set(store.list_keys("rdzv/"))
    assert not any(
        k.decode().split("/")[1] in ("0", "1")
        for k in store.list_keys("rdzv/")
        if k.decode().split("/")[1].isdigit()
    )
    assert store.check([k_result(4)])


def test_heterogeneous_slots_allowed_when_configured():
    out = assign_group_ranks(
        [_node(0, slots=2), _node(1, slots=4)], 1, None,
        require_equal_slots=False,
    )
    ranks = {nid: a["group_rank"] for nid, a in out.items()}
    assert sorted(ranks.values()) == [0, 1]


def test_full_round_mixed_slots(rdzv_store):
    """A v5e-4 host joins a v5e-8 fleet: global ranks offset by each node's
    ACTUAL slot count (reference heterogeneous agent groups)."""
    host = RendezvousHost(
        rdzv_store(), min_nodes=2, max_nodes=2, settle_time=0.2,
        require_equal_slots=False,
    )
    host.bootstrap()
    host.open_round()
    results = {}
    slots = {"small": 4, "big": 8}
    threads = [
        threading.Thread(
            target=_run_join,
            args=(rdzv_store, NodeDesc.create(name, slots=n), results),
        )
        for name, n in slots.items()
    ]
    for t in threads:
        t.start()
    host.close_round_when_ready(timeout=20.0)
    for t in threads:
        t.join(timeout=20.0)
    assert len(results) == 2
    for r in results.values():
        assert not isinstance(r, Exception), r
        assert r.global_world_size == 12
        assert r.group_world_size == 2
    by_rank = sorted(results.values(), key=lambda r: r.group_rank)
    # first node's workers are ranks [0, its_slots); second starts after it
    first_slots = slots[
        [k for k, v in results.items() if v is by_rank[0]][0]
    ]
    assert by_rank[0].rank_offset == 0
    assert by_rank[1].rank_offset == first_slots


def test_mixed_slots_rejected_by_default(rdzv_store):
    host = RendezvousHost(rdzv_store(), min_nodes=2, max_nodes=2, settle_time=0.2)
    host.bootstrap()
    host.open_round()
    results = {}
    threads = [
        threading.Thread(
            target=_run_join,
            args=(rdzv_store, NodeDesc.create(name, slots=n), results),
        )
        for name, n in {"a": 2, "b": 4}.items()
    ]
    for t in threads:
        t.start()
    with pytest.raises(Exception):
        host.close_round_when_ready(timeout=10.0)
    for t in threads:
        t.join(timeout=5.0)
