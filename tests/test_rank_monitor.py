"""Rank monitor server/client tests.

Mirrors reference ``tests/fault_tolerance/unit/test_rank_monitor_server.py``:
runs a real RankMonitorServer (in-thread asyncio here; subprocess covered by
launcher tests) and exercises heartbeat/section timeout detection with an
injectable kill function.
"""

import asyncio
import threading
import time

import pytest

from tpu_resiliency.fault_tolerance.config import FaultToleranceConfig
from tpu_resiliency.fault_tolerance.data import RankInfo
from tpu_resiliency.fault_tolerance.rank_monitor_client import RankMonitorClient
from tpu_resiliency.fault_tolerance.rank_monitor_server import RankMonitorServer


class ServerThread:
    """Run RankMonitorServer's asyncio loop on a daemon thread."""

    def __init__(self, cfg, socket_path, kill_fn=None):
        self.server = RankMonitorServer(cfg, socket_path, kill_fn=kill_fn)
        self._loop = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        assert self._started.wait(5)

    def _run(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(self.server.run_async(self._started))
        except Exception:
            pass

    def stop(self):
        if self._loop:
            self._loop.call_soon_threadsafe(
                lambda: [t.cancel() for t in asyncio.all_tasks(self._loop)]
            )
        self._thread.join(timeout=3)


@pytest.fixture
def monitor(tmp_path):
    def make(cfg, kill_fn=None):
        path = str(tmp_path / "monitor.sock")
        st = ServerThread(cfg, path, kill_fn=kill_fn)
        return st, path

    made = []

    def wrapper(cfg, kill_fn=None):
        st, path = make(cfg, kill_fn)
        made.append(st)
        return st, path

    yield wrapper
    for st in made:
        st.stop()


def _client(cfg, path, rank=0):
    client = RankMonitorClient(cfg)
    client.init_workload_monitoring(
        socket_path=path, rank_info=RankInfo(global_rank=rank, local_rank=rank, pid=12345)
    )
    return client


def test_init_and_heartbeat(monitor):
    cfg = FaultToleranceConfig(workload_check_interval=0.1, skip_section_response=False)
    st, path = monitor(cfg)
    client = _client(cfg, path)
    assert client.hb_timeouts.initial == cfg.initial_rank_heartbeat_timeout
    for _ in range(3):
        client.send_heartbeat()
    assert st.server.state.last_hb is not None
    client.shutdown_workload_monitoring()


def test_heartbeat_timeout_kills_rank(monitor):
    killed = []
    cfg = FaultToleranceConfig(
        initial_rank_heartbeat_timeout=0.3,
        rank_heartbeat_timeout=0.3,
        workload_check_interval=0.05,
    )
    st, path = monitor(cfg, kill_fn=lambda pid, sig: killed.append((pid, sig)))
    client = _client(cfg, path)
    client.send_heartbeat()
    deadline = time.monotonic() + 3.0
    while not killed and time.monotonic() < deadline:
        time.sleep(0.05)
    assert killed and killed[0][0] == 12345


def test_no_initial_heartbeat_detected(monitor):
    killed = []
    cfg = FaultToleranceConfig(
        initial_rank_heartbeat_timeout=0.2,
        workload_check_interval=0.05,
    )
    st, path = monitor(cfg, kill_fn=lambda pid, sig: killed.append(pid))
    client = _client(cfg, path)  # never heartbeats; keep alive so UDS stays open
    assert client.is_initialized
    deadline = time.monotonic() + 3.0
    while not killed and time.monotonic() < deadline:
        time.sleep(0.05)
    assert killed == [12345]


def test_section_timeout(monitor):
    killed = []
    cfg = FaultToleranceConfig(
        initial_rank_heartbeat_timeout=None,
        rank_heartbeat_timeout=None,
        rank_section_timeouts={"step": 0.2},
        workload_check_interval=0.05,
        skip_section_response=False,
    )
    st, path = monitor(cfg, kill_fn=lambda pid, sig: killed.append(pid))
    client = _client(cfg, path)
    client.start_section("step")
    time.sleep(0.1)
    client.end_section("step")   # within timeout: fine
    assert not killed
    client.start_section("step")  # now hang inside the section
    deadline = time.monotonic() + 3.0
    while not killed and time.monotonic() < deadline:
        time.sleep(0.05)
    assert killed == [12345]


def test_out_of_section_timeout(monitor):
    killed = []
    cfg = FaultToleranceConfig(
        initial_rank_heartbeat_timeout=None,
        rank_heartbeat_timeout=None,
        rank_section_timeouts={"step": 5.0},
        rank_out_of_section_timeout=0.2,
        workload_check_interval=0.05,
        skip_section_response=False,
    )
    st, path = monitor(cfg, kill_fn=lambda pid, sig: killed.append(pid))
    client = _client(cfg, path)
    client.start_section("step")
    client.end_section("step")
    # now "hang" outside any section
    deadline = time.monotonic() + 3.0
    while not killed and time.monotonic() < deadline:
        time.sleep(0.05)
    assert killed == [12345]


def test_calculated_timeouts_roundtrip(monitor):
    cfg = FaultToleranceConfig(workload_check_interval=5.0, skip_section_response=False)
    st, path = monitor(cfg)
    client = _client(cfg, path)
    client.send_heartbeat()
    time.sleep(0.05)
    client.send_heartbeat()
    new = client.calculate_and_set_hb_timeouts()
    assert new.were_calculated
    assert st.server.hb_timeouts.were_calculated
    assert st.server.hb_timeouts.initial == pytest.approx(new.initial)
    # persistence roundtrip: state_dict -> new client -> restore on init
    state = client.state_dict()
    client.shutdown_workload_monitoring()
    client2 = RankMonitorClient(cfg)
    client2.load_state_dict(state)
    client2.init_workload_monitoring(
        socket_path=path, rank_info=RankInfo(global_rank=0, local_rank=0, pid=12345)
    )
    assert client2.hb_timeouts.were_calculated
    assert client2.hb_timeouts.initial == pytest.approx(new.initial)
    client2.shutdown_workload_monitoring()


def test_monitor_in_subprocess(tmp_path):
    """Full-fidelity path: monitor as a separate process, like the launcher runs it."""
    cfg = FaultToleranceConfig(workload_check_interval=0.1, skip_section_response=False)
    path = str(tmp_path / "sub.sock")
    proc, ctrl = RankMonitorServer.run_in_subprocess(cfg, path)
    try:
        client = _client(cfg, path)
        client.send_heartbeat()
        ctrl.send({"cmd": "cycle", "cycle": 7})
        time.sleep(0.5)
        # reconnect gets the new cycle number
        client.shutdown_workload_monitoring()
        client2 = _client(cfg, path)
        assert client2.cycle == 7
        client2.shutdown_workload_monitoring()
    finally:
        ctrl.send({"cmd": "shutdown"})
        proc.join(timeout=5)
        if proc.is_alive():
            proc.terminate()


def test_stale_connection_cannot_mutate_state(monitor):
    """A lingering previous worker's messages are refused once a new worker
    INITs (heartbeats, sections, timeout updates)."""
    cfg = FaultToleranceConfig(workload_check_interval=5.0, skip_section_response=False)
    st, path = monitor(cfg)
    old = _client(cfg, path, rank=0)
    old.send_heartbeat()
    time.sleep(0.02)
    old.send_heartbeat()  # two observed intervals: timeout calc is possible
    new = _client(cfg, path, rank=0)  # new cycle's worker takes ownership
    new.send_heartbeat()
    from tpu_resiliency.fault_tolerance.rank_monitor_client import (
        RankMonitorClientError,
    )

    with pytest.raises(RankMonitorClientError, match="stale connection"):
        old.send_heartbeat()
    with pytest.raises(RankMonitorClientError, match="stale connection"):
        old.calculate_and_set_hb_timeouts()
    # the owner still works
    new.send_heartbeat()
    new.shutdown_workload_monitoring()
    old.shutdown_workload_monitoring()


def test_post_mortem_op_rings_on_hang(monitor):
    """On a hang kill, the monitor attaches the rank's straggler op-ring
    arena (named shm survives the wedge) and captures top-op stats — the
    CUPTI buffers-outlive-the-launch property."""
    from tpu_resiliency.straggler import OpRingArena

    arena = OpRingArena(max_ops=8, capacity=32)
    if not arena.native:
        arena.close()
        pytest.skip("native ring library unavailable")
    try:
        idx = arena.intern("train_step")
        for v in (0.1, 0.2, 0.3):
            arena.push(idx, v)
        killed = []
        cfg = FaultToleranceConfig(
            initial_rank_heartbeat_timeout=0.4,
            rank_heartbeat_timeout=0.3,
            workload_check_interval=0.05,
            skip_section_response=False,
        )
        st, path = monitor(cfg, kill_fn=lambda pid, sig: killed.append(pid))
        client = RankMonitorClient(cfg)
        client.init_workload_monitoring(
            socket_path=path,
            rank_info=RankInfo(global_rank=0, local_rank=0, pid=4242),
            op_ring_shm=arena.shm_name,
        )
        client.send_heartbeat()
        # now "hang": no more heartbeats; the monitor should read the rings
        # BEFORE killing
        deadline = time.time() + 5
        while not killed and time.time() < deadline:
            time.sleep(0.05)
        assert killed == [4242]
        # the server read the rings BEFORE the kill: the HANG_DETECTED
        # profiling event carries the captured top-op summary
        from tpu_resiliency.utils.profiling import get_recorder

        deadline = time.time() + 2
        post = []
        while time.time() < deadline and not post:
            post = [
                e for e in get_recorder().events
                if e.get("event") == "hang_detected" and e.get("post_mortem_ops")
            ]
            time.sleep(0.05)
        assert post, "server did not capture post-mortem op stats"
        ops = post[-1]["post_mortem_ops"]
        assert ops[0]["op"] == "train_step" and ops[0]["count"] == 3
        client.shutdown_workload_monitoring()
    finally:
        arena.close()
