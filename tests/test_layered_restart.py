"""Layered restart: in-process ring UNDER the in-job ring.

Reference analog: ``tests/fault_tolerance/unit/test_layered_restart_v1.py``
— the composition contract from SURVEY.md §1: faults the wrapper can absorb
never reach the launcher; faults it cannot (dead process) escalate.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

from tpu_resiliency.utils.env import disarm_platform_sitecustomize

REPO = Path(__file__).resolve().parent.parent
WORKER = str(REPO / "tests" / "workloads" / "layered_worker.py")


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_layered(tmp_path, scenario, timeout=150, extra_env=None):
    env = dict(os.environ)
    disarm_platform_sitecustomize(env)
    env.update(
        {
            "TPURX_REPO": str(REPO),
            "LAYERED_SCENARIO": scenario,
            "TOY_CKPT": str(tmp_path / "progress.txt"),
            "TPURX_FT_ENABLE_DEVICE_HEALTH_CHECK": "0",
            "TPURX_FT_WORKERS_STOP_TIMEOUT": "3.0",
            "TPURX_FT_RDZV_ROUND_TIMEOUT": "30.0",
            "JAX_PLATFORMS": "cpu",
        }
    )
    env.update(extra_env or {})
    proc = subprocess.run(
        [
            sys.executable, "-m", "tpu_resiliency.fault_tolerance.launcher",
            "--nnodes", "1", "--nproc-per-node", "2",
            "--rdzv-endpoint", f"127.0.0.1:{free_port()}",
            "--host-store", "--max-restarts", "3",
            "--monitor-interval", "0.05",
            WORKER,
        ],
        cwd=str(REPO), env=env, capture_output=True, text=True, timeout=timeout,
    )
    if proc.returncode != 0:
        print("STDOUT:", proc.stdout[-4000:])
        print("STDERR:", proc.stderr[-4000:])
    return proc


def test_inner_fault_absorbed_by_inprocess_ring(tmp_path):
    proc = run_layered(tmp_path, "inner")
    assert proc.returncode == 0
    # the wrapper recovered: both ranks finished at wrapper-iteration 1...
    assert proc.stdout.count("ret=done@1") == 2
    # ...and the LAUNCHER never saw a failure (no new cycle)
    assert "worker failure detected" not in proc.stderr
    assert "cycle=1" not in proc.stdout
    # the nested-restarter protocol surfaced the recovery phases
    assert "[NestedRestarter] name=[InProcess] state=handling_start" in proc.stdout
    assert "[NestedRestarter] name=[InProcess] state=completed" in proc.stdout
    # the abort ladder ran with recorded per-stage outcomes
    blob = proc.stdout + proc.stderr
    assert "abort ladder:" in blob
    assert "fingerprint=released" in blob


def test_inner_fault_with_shrink_mesh_stage_enabled(tmp_path):
    """The opt-in ShrinkMeshStage on the in-process recovery path: with no
    distributed client it releases by clearing caches+backends, recovery
    still completes in-process, and the outcome is recorded — the ladder's
    rung order and gating exercised end to end under the real launcher."""
    proc = run_layered(tmp_path, "inner", extra_env={"TPURX_SHRINK_MESH": "1"})
    assert proc.returncode == 0
    assert proc.stdout.count("ret=done@1") == 2
    assert "worker failure detected" not in proc.stderr
    blob = proc.stdout + proc.stderr
    assert "shrink_mesh=released" in blob


def test_stalled_collective_recovered_through_ladder_with_verdict(tmp_path):
    """The wedged-collective case the ladder absorbs IN-PROCESS: rank 1
    parks ping-less on a 'collective', the quorum tripwire names the stale
    rank, every rank's ladder publishes its dispatch tail, and the
    trace-analyzer verdict cites the in-flight op and the lagging rank
    from the at-abort fingerprints (VERDICT r5 'do this' #5)."""
    proc = run_layered(
        tmp_path, "stall", timeout=240,
        extra_env={
            # host ring stays the distant backstop; quorum owns detection
            "WRAP_SOFT_TIMEOUT": "60", "WRAP_HARD_TIMEOUT": "120",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        },
    )
    assert proc.returncode == 0
    blob = proc.stdout + proc.stderr
    assert "stalling: parked on a collective" in proc.stdout
    # detection came from the quorum tripwire, not a host timeout
    assert "quorum tripwire: heartbeat stale" in blob
    # both ranks recovered in-process; the launcher never saw a failure
    assert proc.stdout.count("ret=done@1") == 2
    assert "worker failure detected" not in proc.stderr
    # the at-abort fingerprint verdict names the op and the lagging rank
    assert "abort fingerprint verdict" in blob
    assert "unified_allreduce" in blob
    verdict_lines = [
        l for l in blob.splitlines() if "abort fingerprint verdict" in l
    ]
    assert any("culprits=[1]" in l for l in verdict_lines), verdict_lines[:5]


def test_link_degrade_absorbed_below_both_rings(tmp_path):
    """The self-healing collective layer UNDER the layered stack
    (docs/collectives.md): rank 1's primary collective lane is armed to
    stall past its deadline every call (``TPURX_FAULT=coll_stall``), the
    wrapped ``device_max_reduce`` walks retry → re-layout in process, and
    a shrink-only probe trips the Wrapper-installed DegradeToShrink hook
    running the real opt-in ShrinkMeshStage as a TARGETED rung.  Neither
    restart ring fires: both ranks finish at wrapper-iteration 0 and the
    launcher records zero cycles."""
    proc = run_layered(
        tmp_path, "degrade", timeout=240,
        extra_env={
            "LAYERED_STEPS": "8",
            "TPURX_FAULT": "coll_stall",
            "TPURX_FAULT_RANKS": "1",
            "TPURX_COLL_DEADLINE_MS": "500",
            "TPURX_COLL_RETRIES": "1",
            "TPURX_SHRINK_MESH": "1",
        },
    )
    assert proc.returncode == 0
    blob = proc.stdout + proc.stderr
    # absorbed BELOW both rings: no wrapper restart (iteration stays 0),
    # no launcher cycle
    assert proc.stdout.count("ret=done@0") == 2
    assert "worker failure detected" not in proc.stderr
    assert "cycle=1" not in proc.stdout
    # the armed rank walked the ladder: deadline trips and degrades; the
    # healthy rank never degraded
    marks = {}
    for line in proc.stdout.splitlines():
        # worker stdout arrives through the log funnel with an [rN] prefix
        if "colldeg[" in line:
            mark = line[line.index("colldeg["):]
            rank = int(mark.split("[")[1].split("]")[0])
            kv = dict(p.split("=") for p in mark.split()[1:])
            marks[rank] = kv
    assert set(marks) == {0, 1}, blob[-3000:]
    assert int(marks[1]["degrades"]) >= 1, marks
    assert int(marks[1]["timeouts"]) >= 1, marks
    assert int(marks[0]["degrades"]) == 0, marks
    # the re-layout rung engaged on the armed rank's step collective...
    assert "collective degrade: op=device_max_reduce" in blob
    # ...and the shrink probe reached the targeted ShrinkMeshStage through
    # the degrade hook, completing on the fallback lane
    assert "degrade-to-shrink: op=shrink_probe" in blob
    assert "shrink_mesh=released" in blob
    assert "shrink probe -> shrunk" in proc.stdout


def test_outer_fault_escalates_to_launcher(tmp_path):
    proc = run_layered(tmp_path, "outer")
    assert proc.returncode == 0
    # the process death escalated: launcher restarted the group
    assert "worker failure detected" in proc.stderr
    # cycle 1 ran clean to completion on both ranks
    assert proc.stdout.count("cycle=1 ret=done@0") == 2


def test_wedged_device_call_hard_killed_and_ring_recovers(tmp_path):
    """The documented wedged-device contract, exercised END TO END (VERDICT
    r4 'do this' #3 — previously closed only by abort.py's docstring): a
    rank blocks forever inside a real device program (jit'd infinite
    while_loop — stuck in PJRT C++ with the GIL released, exactly how a
    collective with a missing participant presents), its pings and
    pending-call auto-stamps freeze, the exec'd monitor process records
    SOFT_TIMEOUT, the in-process ring's async raise cannot land, the hard
    timeout SIGKILLs the rank, and the launcher's in-job ring
    re-rendezvouses a clean cycle.  Ref: reference
    ``inprocess/monitor_process.py:269-288``, ``nested_restarter.py:36-107``.
    """
    proc = run_layered(
        tmp_path, "wedged", timeout=240,
        extra_env={"WRAP_SOFT_TIMEOUT": "6", "WRAP_HARD_TIMEOUT": "12"},
    )
    assert proc.returncode == 0
    blob = proc.stdout + proc.stderr
    # the wedge engaged, and only the monitor process could break it
    assert "wedging in a device program" in proc.stdout
    assert "killing" in blob, blob[-3000:]  # monitor-process hard-kill fired
    # the launcher ring took over and recovered the job
    assert "worker failure detected" in proc.stderr
    assert proc.stdout.count("cycle=1 ret=done@0") == 2
    # the nested-restarter protocol surfaced the recovery attempt
    assert "[NestedRestarter] name=[InProcess] state=handling_start" in blob
    # the abort ladder still ran on the wedged rank (its monitor THREAD is
    # schedulable even while the main thread is stuck in C) and published
    # the at-abort fingerprint before the hard-kill; the in-flight-op
    # verdict itself is covered by the stall scenario, where a survivor
    # runs the restart path (here rank 0 completed before the escalation)
    assert "abort ladder: fingerprint=released" in blob


def test_abort_ladder_under_lock_order_sanitizer_clean_witness(tmp_path):
    """Soak smoke lane for the runtime lock-order sanitizer: the layered
    restart e2e (inner fault -> full abort ladder -> in-process recovery)
    runs with TPURX_SANITIZE=1.  The sanitizer wraps every lock the wrapper,
    monitor thread, quorum tripwire, and checkpoint machinery create, and
    must observe NO runtime lock-order cycle on the abort-ladder path — a
    cycle would have raised LockOrderViolation and failed the run.  The
    per-process witness files it leaves are the confirm/prune input for
    `tpurx-lint --witness` (see docs/lint.md)."""
    import glob
    import json

    wit_tpl = str(tmp_path / "witness.r%r.p%p.jsonl")
    proc = run_layered(
        tmp_path, "inner",
        extra_env={
            "TPURX_SANITIZE": "1",
            "TPURX_SANITIZE_WITNESS_PATH": wit_tpl,
        },
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    # recovery completed exactly as without the sanitizer
    assert proc.stdout.count("ret=done@1") == 2
    assert "worker failure detected" not in proc.stderr
    assert "abort ladder:" in proc.stdout + proc.stderr

    paths = glob.glob(str(tmp_path / "witness.r*.jsonl"))
    assert paths, "sanitizer produced no witness files"
    edges = 0
    for p in paths:
        for line in open(p):
            rec = json.loads(line)
            assert rec["event"] != "cycle", (
                f"runtime lock-order cycle on the abort path: {rec}")
            if rec["event"] == "edge":
                edges += 1
    assert edges > 0, "sanitizer observed no lock acquisitions at all"
