"""Layered restart: in-process ring UNDER the in-job ring.

Reference analog: ``tests/fault_tolerance/unit/test_layered_restart_v1.py``
— the composition contract from SURVEY.md §1: faults the wrapper can absorb
never reach the launcher; faults it cannot (dead process) escalate.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

from tpu_resiliency.utils.env import disarm_platform_sitecustomize

REPO = Path(__file__).resolve().parent.parent
WORKER = str(REPO / "tests" / "workloads" / "layered_worker.py")


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_layered(tmp_path, scenario, timeout=150, extra_env=None):
    env = dict(os.environ)
    disarm_platform_sitecustomize(env)
    env.update(
        {
            "TPURX_REPO": str(REPO),
            "LAYERED_SCENARIO": scenario,
            "TOY_CKPT": str(tmp_path / "progress.txt"),
            "TPURX_FT_ENABLE_DEVICE_HEALTH_CHECK": "0",
            "TPURX_FT_WORKERS_STOP_TIMEOUT": "3.0",
            "TPURX_FT_RDZV_ROUND_TIMEOUT": "30.0",
            "JAX_PLATFORMS": "cpu",
        }
    )
    env.update(extra_env or {})
    proc = subprocess.run(
        [
            sys.executable, "-m", "tpu_resiliency.fault_tolerance.launcher",
            "--nnodes", "1", "--nproc-per-node", "2",
            "--rdzv-endpoint", f"127.0.0.1:{free_port()}",
            "--host-store", "--max-restarts", "3",
            "--monitor-interval", "0.05",
            WORKER,
        ],
        cwd=str(REPO), env=env, capture_output=True, text=True, timeout=timeout,
    )
    if proc.returncode != 0:
        print("STDOUT:", proc.stdout[-4000:])
        print("STDERR:", proc.stderr[-4000:])
    return proc


def test_inner_fault_absorbed_by_inprocess_ring(tmp_path):
    proc = run_layered(tmp_path, "inner")
    assert proc.returncode == 0
    # the wrapper recovered: both ranks finished at wrapper-iteration 1...
    assert proc.stdout.count("ret=done@1") == 2
    # ...and the LAUNCHER never saw a failure (no new cycle)
    assert "worker failure detected" not in proc.stderr
    assert "cycle=1" not in proc.stdout
    # the nested-restarter protocol surfaced the recovery phases
    assert "[NestedRestarter] name=[InProcess] state=handling_start" in proc.stdout
    assert "[NestedRestarter] name=[InProcess] state=completed" in proc.stdout


def test_outer_fault_escalates_to_launcher(tmp_path):
    proc = run_layered(tmp_path, "outer")
    assert proc.returncode == 0
    # the process death escalated: launcher restarted the group
    assert "worker failure detected" in proc.stderr
    # cycle 1 ran clean to completion on both ranks
    assert proc.stdout.count("cycle=1 ret=done@0") == 2


def test_wedged_device_call_hard_killed_and_ring_recovers(tmp_path):
    """The documented wedged-device contract, exercised END TO END (VERDICT
    r4 'do this' #3 — previously closed only by abort.py's docstring): a
    rank blocks forever inside a real device program (jit'd infinite
    while_loop — stuck in PJRT C++ with the GIL released, exactly how a
    collective with a missing participant presents), its pings and
    pending-call auto-stamps freeze, the exec'd monitor process records
    SOFT_TIMEOUT, the in-process ring's async raise cannot land, the hard
    timeout SIGKILLs the rank, and the launcher's in-job ring
    re-rendezvouses a clean cycle.  Ref: reference
    ``inprocess/monitor_process.py:269-288``, ``nested_restarter.py:36-107``.
    """
    proc = run_layered(
        tmp_path, "wedged", timeout=240,
        extra_env={"WRAP_SOFT_TIMEOUT": "6", "WRAP_HARD_TIMEOUT": "12"},
    )
    assert proc.returncode == 0
    blob = proc.stdout + proc.stderr
    # the wedge engaged, and only the monitor process could break it
    assert "wedging in a device program" in proc.stdout
    assert "killing" in blob, blob[-3000:]  # monitor-process hard-kill fired
    # the launcher ring took over and recovered the job
    assert "worker failure detected" in proc.stderr
    assert proc.stdout.count("cycle=1 ret=done@0") == 2
    # the nested-restarter protocol surfaced the recovery attempt
    assert "[NestedRestarter] name=[InProcess] state=handling_start" in blob
