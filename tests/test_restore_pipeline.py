"""Parallel verified restore pipeline tests.

The restore engine (``async_ckpt/writer._RestoreEngine``) mirrors the write
engine: a plan from metadata.json, size-bucketed chunked reads on a thread
pool, crc verified in-flight, per-leaf device_put overlap.  Everything here
runs tier-1-sized (small states, ``threads=2``) so the pipeline is
exercised on every CI pass without the slow 1 GiB bench lane.
"""

import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_resiliency.checkpointing import (
    AsyncCheckpointer,
    CheckpointCorruptError,
    LocalCheckpointManager,
    TensorAwareTree,
    load_checkpoint,
    verify_blob_file,
)
from tpu_resiliency.checkpointing.async_ckpt.writer import (
    resolve_restore_threads,
    resolve_write_threads,
)
from tpu_resiliency.checkpointing.coverage import (
    contiguous_offset,
    covers,
    union_volume,
)
from tpu_resiliency.checkpointing.integrity import FOOTER_BYTES
from tpu_resiliency.telemetry import get_registry
from tpu_resiliency.utils.dtypes import coerce_dtype


def _counter_sum(name):
    m = get_registry().get(name)
    if m is None:
        return 0.0
    return sum(v.get("value", 0.0) for _l, v in m._sample_rows())


def make_tree():
    return {
        "w": jax.device_put(np.arange(100_000, dtype=np.float32)),
        "b": jnp.zeros((33,), dtype=jnp.float32),
        "bf16": jax.device_put(np.arange(2048).astype("bfloat16")),
        "step": jnp.int32(7),
        "plain_numpy": np.arange(11, dtype=np.int64),
    }


def assert_trees_equal(a, b):
    la, _ = jax.tree_util.tree_flatten(a)
    lb, _ = jax.tree_util.tree_flatten(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _bitflip(path, off):
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))


# -- the pipeline ------------------------------------------------------------


def test_parallel_restore_smoke_threads2(tmp_path):
    """The tier-1 restore smoke: full save -> parallel verified restore on a
    2-thread pool, stats populated, telemetry counters moved."""
    tree = make_tree()
    d = str(tmp_path / "ck")
    ckpt = AsyncCheckpointer()
    try:
        ckpt.save(tree, d, extra_metadata={"iteration": 1})
    finally:
        ckpt.close()
    bytes_before = _counter_sum("tpurx_ckpt_restore_bytes_total")
    stats = {}
    restored = load_checkpoint(d, tree, threads=2, stats=stats)
    assert_trees_equal(tree, restored)
    assert stats["threads"] == 2
    assert stats["leaves"] == 5
    assert stats["shards"] >= 5
    assert stats["bytes_read"] > 0
    assert stats["verify_ns"] > 0  # crc verification on by default
    assert stats["restore_ns"] > 0
    delta = _counter_sum("tpurx_ckpt_restore_bytes_total") - bytes_before
    assert delta == stats["bytes_read"]


def test_parallel_matches_serial(tmp_path):
    tree = make_tree()
    d = str(tmp_path / "ck")
    ckpt = AsyncCheckpointer()
    try:
        ckpt.save(tree, d, extra_metadata={"iteration": 1})
    finally:
        ckpt.close()
    par = load_checkpoint(d, tree, threads=3)
    ser = load_checkpoint(d, tree, serial=True)
    assert_trees_equal(par, ser)
    assert_trees_equal(par, tree)


def test_sharded_leaves_parallel_restore(tmp_path):
    """Row sharding exercises the direct-into-leaf-buffer path (contiguous
    boxes), column sharding the scratch-then-place path."""
    devs = jax.devices()
    assert len(devs) == 8
    mesh = Mesh(np.array(devs), ("x",))
    rows = jax.device_put(
        np.arange(64 * 32, dtype=np.float32).reshape(64, 32),
        NamedSharding(mesh, P("x", None)),
    )
    cols = jax.device_put(
        np.arange(16 * 64, dtype=np.float32).reshape(16, 64),
        NamedSharding(mesh, P(None, "x")),
    )
    tree = {"rows": rows, "cols": cols, "s": jnp.float32(3.0)}
    d = str(tmp_path / "ck")
    ckpt = AsyncCheckpointer()
    try:
        ckpt.save(tree, d, extra_metadata={"iteration": 1})
    finally:
        ckpt.close()
    restored = load_checkpoint(d, tree, threads=2)
    assert_trees_equal(tree, restored)
    assert restored["rows"].sharding.is_equivalent_to(rows.sharding, 2)
    assert restored["cols"].sharding.is_equivalent_to(cols.sharding, 2)


def test_corrupt_shard_cancels_and_names_shard(tmp_path):
    """A flipped bit mid-parallel-restore: the error names the shard file,
    queued read tasks are dropped, and no reader threads leak."""
    tree = make_tree()
    d = str(tmp_path / "ck")
    ckpt = AsyncCheckpointer()
    try:
        ckpt.save(tree, d, extra_metadata={"iteration": 1})
    finally:
        ckpt.close()
    # corrupt the biggest shard ("w": leaf order is sorted dict keys)
    import glob

    shard = sorted(
        glob.glob(os.path.join(d, "process_0", "*.bin")), key=os.path.getsize
    )[-1]
    _bitflip(shard, off=4242)
    # resident=False: this test exercises the DISK lane — the warm
    # shm-resident source would (correctly) never see the flipped bit
    with pytest.raises(
        CheckpointCorruptError, match=os.path.basename(shard)
    ) as ei:
        load_checkpoint(d, tree, threads=2, resident=False)
    assert "corrupt chunk" in str(ei.value)
    assert not [
        t
        for t in threading.enumerate()
        if t.name.startswith("tpurx-ckpt-restore-") and t.is_alive()
    ], "restore reader threads leaked after corruption abort"


def test_corrupt_shard_then_local_fallback_ladder(tmp_path):
    """The restore-side detection feeds the local-manager recovery story:
    a corrupt newest iteration is quarantined by the (threaded) validity
    verifier and load(fallback=True) restores the next-oldest instead."""
    mgr = LocalCheckpointManager(str(tmp_path), rank=0, world_size=1)
    t1 = {"w": np.arange(50, dtype=np.float32)}
    t2 = {"w": np.arange(50, dtype=np.float32) * 2}
    mgr.save(t1, iteration=1, is_async=False)
    mgr.save(t2, iteration=2, is_async=False)
    _bitflip(mgr._blob_path(2, 0), off=200)
    tree, it = mgr.load(t2, fallback=True)
    assert it == 1
    np.testing.assert_array_equal(tree["w"], t1["w"])
    assert os.path.exists(mgr._blob_path(2, 0) + ".corrupt")


def test_legacy_digest_off_parallel_restore(tmp_path):
    """digest=False saves carry no crcs — the parallel reader still
    restores them (size check only, like the serial legacy path)."""
    tree = make_tree()
    d = str(tmp_path / "ck")
    ckpt = AsyncCheckpointer(digest=False)
    try:
        ckpt.save(tree, d, extra_metadata={"iteration": 1})
    finally:
        ckpt.close()
    stats = {}
    restored = load_checkpoint(d, tree, threads=2, stats=stats)
    assert_trees_equal(tree, restored)
    assert stats["verify_ns"] == 0  # nothing recorded to verify against


def test_restore_threads_resolution(monkeypatch):
    assert resolve_restore_threads(5) == 5
    monkeypatch.setenv("TPURX_CKPT_RESTORE_THREADS", "3")
    assert resolve_restore_threads() == 3
    monkeypatch.setenv("TPURX_CKPT_RESTORE_THREADS", "junk")
    assert resolve_restore_threads() == resolve_write_threads(None)
    monkeypatch.delenv("TPURX_CKPT_RESTORE_THREADS")
    assert resolve_restore_threads() == resolve_write_threads(None)


# -- satellite: no-copy dtype coercion ---------------------------------------


def test_coerce_dtype_no_copy():
    a = np.arange(100, dtype=np.float32)
    assert coerce_dtype(a, np.float32) is a  # matching dtype: NO copy
    assert coerce_dtype(a, "float32") is a
    b = coerce_dtype(a, np.float64)
    assert b is not a and b.dtype == np.float64
    np.testing.assert_array_equal(a, b)


def test_state_dict_to_tree_no_copy_on_matching_dtype():
    src = {"w": jax.device_put(np.arange(32, dtype=np.float32))}
    tat = TensorAwareTree.from_tree(src)
    blob = tat.to_bytes()
    parsed = TensorAwareTree.from_bytes(blob, copy=False)
    out = parsed.to_tree_like(src)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(src["w"]))


# -- satellite: interval/volume coverage accounting --------------------------


def test_union_volume_and_covers():
    assert union_volume((4, 4), [[(0, 4), (0, 4)]]) == 16
    # overlap counted once
    assert union_volume((4, 4), [[(0, 3), (0, 4)], [(1, 4), (0, 4)]]) == 16
    assert union_volume((4, 4), [[(0, 2), (0, 4)], [(3, 4), (0, 4)]]) == 12
    assert covers((4, 4), [[(0, 2), (0, 4)], [(2, 4), (0, 4)]])
    assert not covers((4, 4), [[(0, 2), (0, 4)], [(3, 4), (0, 4)]])
    # scalar / zero-size shapes
    assert union_volume((), [[]]) == 1
    assert covers((), [[]])
    assert covers((0, 5), [])
    # clipping out-of-range boxes
    assert union_volume((4,), [[(-2, 10)]]) == 4


def test_contiguous_offset():
    # whole leaf
    assert contiguous_offset((8, 4), [(0, 8), (0, 4)], 4) == (0, 8 * 4 * 4)
    # leading-axis shard
    assert contiguous_offset((8, 4), [(2, 4), (0, 4)], 4) == (2 * 16, 2 * 16)
    # inner-axis shard of a multi-row array: not contiguous
    assert contiguous_offset((8, 4), [(0, 8), (0, 2)], 4) is None
    # inner-axis shard behind a singleton leading dim: contiguous
    assert contiguous_offset((1, 8, 4), [(0, 1), (2, 4), (0, 4)], 4) == (
        2 * 16,
        2 * 16,
    )


# -- streaming blob verification ---------------------------------------------


def test_verify_blob_file_streaming(tmp_path):
    tat = TensorAwareTree.from_tree({"a": np.arange(5000, dtype=np.float32)})
    blob = tat.to_bytes()
    path = str(tmp_path / "b.tpurx")
    with open(path, "wb") as f:
        f.write(blob)
    assert verify_blob_file(path) == len(blob) - FOOTER_BYTES
    # bit rot in the payload
    _bitflip(path, off=len(blob) // 2)
    with pytest.raises(CheckpointCorruptError, match="crc mismatch"):
        verify_blob_file(path)
    # truncation
    with open(path, "r+b") as f:
        f.truncate(len(blob) - 100)
    with pytest.raises(CheckpointCorruptError, match="truncated|magic"):
        verify_blob_file(path)
    # no footer at all
    with open(path, "wb") as f:
        f.write(b"x" * 50)
    with pytest.raises(CheckpointCorruptError, match="magic"):
        verify_blob_file(path)


# -- satellite: scrubber racing a concurrent restore -------------------------


def test_scrubber_races_concurrent_verify_single_quarantine(tmp_path):
    """Scrubber and a restore detecting the SAME rot concurrently: exactly
    one quarantine is counted (rename-winner), no ``.corrupt.corrupt``
    double-rename, holdings drop the blob once."""
    mgr = LocalCheckpointManager(str(tmp_path), rank=0, world_size=1)
    t1 = {"w": np.arange(500, dtype=np.float32)}
    mgr.save(t1, iteration=1, is_async=False)
    mgr.save({"w": t1["w"] * 3}, iteration=2, is_async=False)
    _bitflip(mgr._blob_path(2, 0), off=300)
    before = _counter_sum("tpurx_ckpt_quarantined_total")
    start = threading.Barrier(2)
    results = []

    def _race(site):
        start.wait()
        results.append(mgr.verify_iteration(2, site=site))

    threads = [
        threading.Thread(target=_race, args=("scrub",)),
        threading.Thread(target=_race, args=("local_blob",)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # at least one pass caught the rot; the loser either also caught it
    # (rename race, uncounted) or found the blob already quarantined
    assert False in results
    delta = _counter_sum("tpurx_ckpt_quarantined_total") - before
    assert delta == 1, f"double-quarantine counted ({delta})"
    itdir = mgr._iter_dir(2)
    names = os.listdir(itdir)
    assert "rank_0.tpurx.corrupt" in names
    assert not any(n.endswith(".corrupt.corrupt") for n in names)
    assert 2 not in mgr._holdings()
    # the survivor iteration still loads
    tree, it = mgr.load(t1, fallback=True)
    assert it == 1
    np.testing.assert_array_equal(tree["w"], t1["w"])
