"""Workload-control exclude flow, orbax interop, init_distributed env logic."""

import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


def test_workload_control_exclude_node(tmp_path):
    """A worker asks the launcher to exclude its node (reference
    run_workload_ctrl_test_excl_node.sh): the agent must leave the job."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    worker = tmp_path / "excl_worker.py"
    worker.write_text(
        "import os, sys, time\n"
        f"sys.path.insert(0, {str(REPO)!r})\n"
        "from tpu_resiliency.fault_tolerance import RankMonitorClient\n"
        "from tpu_resiliency.fault_tolerance.data import WorkloadAction\n"
        "c = RankMonitorClient(); c.init_workload_monitoring()\n"
        "c.send_heartbeat()\n"
        "c.send_workload_control_request(WorkloadAction.ExcludeThisNode, 'bad chip')\n"
        "time.sleep(30)\n"  # wait to be stopped by the launcher
    )
    env = dict(os.environ)
    env.update({
        "TPURX_FT_ENABLE_DEVICE_HEALTH_CHECK": "0",
        "TPURX_FT_WORKERS_STOP_TIMEOUT": "2.0",
        "TPURX_FT_RDZV_ROUND_TIMEOUT": "15.0",
    })
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.fault_tolerance.launcher",
         "--nnodes", "1", "--nproc-per-node", "1",
         "--rdzv-endpoint", f"127.0.0.1:{port}",
         "--host-store", "--monitor-interval", "0.05", str(worker)],
        cwd=str(REPO), env=env, capture_output=True, text=True, timeout=90,
    )
    # the only node excluded itself -> the job cannot continue
    assert proc.returncode == 1
    assert "exclude_this_node" in proc.stderr
    assert "not enough healthy nodes" in proc.stderr


def test_init_distributed_env_logic(monkeypatch):
    from tpu_resiliency.parallel.distributed import init_distributed

    # single process: no-op
    monkeypatch.setenv("TPURX_NNODES", "1")
    assert init_distributed() is False
    # coordinator derivation (don't actually initialize — just check inputs
    # via a stub)
    calls = {}

    class FakeDist:
        @staticmethod
        def initialize(coordinator_address, num_processes, process_id):
            calls.update(
                addr=coordinator_address, n=num_processes, pid=process_id
            )

    monkeypatch.setenv("TPURX_NNODES", "4")
    monkeypatch.setenv("TPURX_GROUP_RANK", "2")
    monkeypatch.setenv("TPURX_STORE_ADDR", "10.0.0.5")
    monkeypatch.setenv("TPURX_STORE_PORT", "29400")
    monkeypatch.setattr(jax, "distributed", FakeDist)
    assert init_distributed() is True
    assert calls == {"addr": "10.0.0.5:29401", "n": 4, "pid": 2}


def test_orbax_roundtrip_and_migration(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from tpu_resiliency.checkpointing import load_checkpoint
    from tpu_resiliency.checkpointing.orbax_compat import (
        OrbaxCompatCheckpointer,
        load_orbax_checkpoint,
        migrate_to_tpurx,
    )

    tree = {"w": jnp.arange(12.0).reshape(3, 4), "step": jnp.int32(5)}
    odir = tmp_path / "orbax_ck"
    ck = OrbaxCompatCheckpointer()
    ck.save(tree, str(odir))
    ck.close()
    restored = load_orbax_checkpoint(str(odir), tree)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(tree["w"]))
    # migrate into tpurx format and load through the native path
    tdir = tmp_path / "tpurx_ck"
    migrate_to_tpurx(str(odir), str(tdir), tree)
    migrated = load_checkpoint(str(tdir), tree)
    np.testing.assert_array_equal(np.asarray(migrated["w"]), np.asarray(tree["w"]))
    assert int(migrated["step"]) == 5
