"""Adaptive policy engine tests: windowed rate views, the goodput
estimator's cadence convergence after a fault-rate step, actuator bounds
(clamp/hysteresis/clear), rung-ledger accounting cross-checked against
the abort ladder's own stage-outcome counters, decision journaling +
``tpurx_policy_*`` metrics, and the per-rank PolicyClient poll/apply
path."""

import math
import os

import pytest

from tpu_resiliency.policy import (
    Action,
    Actuator,
    EstimatorInputs,
    GoodputEstimator,
    K_DECISION_LATEST,
    PolicyController,
    RungLedger,
    TelemetryFeed,
    _reset_ledger_for_tests,
    decisions_from_json,
    ledger,
    young_daly_interval,
)
from tpu_resiliency.telemetry.registry import RateWindow, Registry, get_registry
from tpu_resiliency.utils import env


@pytest.fixture(autouse=True)
def _clean_policy_state():
    """Every test starts with no runtime overrides and a fresh ledger."""
    env.clear_runtime_overrides()
    _reset_ledger_for_tests()
    yield
    env.clear_runtime_overrides()
    _reset_ledger_for_tests()


# ---- RateWindow / Counter.rate ---------------------------------------------


class TestRateWindow:
    def test_zero_until_baseline(self):
        w = RateWindow()
        assert w.rate(60.0, 0.0, now=0.0) == 0.0
        assert w.rate(60.0, 5.0, now=10.0) == pytest.approx(0.5)

    def test_steady_rate(self):
        w = RateWindow()
        for i in range(7):
            r = w.rate(60.0, float(i), now=float(i * 10))
        assert r == pytest.approx(0.1)

    def test_counter_reset_rebaselines(self):
        """A cumulative-value decrease (rank restart re-created the
        counter) must re-baseline, never report a negative rate."""
        w = RateWindow()
        w.rate(60.0, 100.0, now=0.0)
        w.rate(60.0, 110.0, now=10.0)
        # restart: the series starts over at 2
        assert w.rate(60.0, 2.0, now=20.0) == 0.0  # no baseline again
        assert w.rate(60.0, 4.0, now=30.0) == pytest.approx(0.2)

    def test_window_expiry_drops_stale_baseline(self):
        """The rate is measured against the oldest IN-WINDOW sample; a
        burst that scrolled out of the window stops inflating it."""
        w = RateWindow()
        w.rate(30.0, 0.0, now=0.0)
        w.rate(30.0, 100.0, now=10.0)  # burst
        # 100s later, only quiet samples are in-window
        w.rate(30.0, 100.0, now=90.0)
        assert w.rate(30.0, 100.0, now=100.0) == 0.0

    def test_counter_rate_view(self):
        reg = Registry(enabled=True)
        c = reg.counter("tpurx_policy_test_events_total")
        assert c.rate(60.0, now=0.0) == 0.0
        c.inc(6)
        assert c.rate(60.0, now=12.0) == pytest.approx(0.5)

    def test_disabled_counter_rate_is_zero(self):
        reg = Registry(enabled=False)
        c = reg.counter("tpurx_policy_test_off_total")
        c.inc()
        assert c.rate(60.0, now=1.0) == 0.0


# ---- estimator --------------------------------------------------------------


def _feed_constant_rate(
    est, start_s, end_s, period_s, count0=0.0, tick_s=5.0, ckpt_cost_s=None
):
    """Feed cumulative exception counts growing one per ``period_s``."""
    count = count0
    t = start_s
    while t < end_s:
        count = count0 + (t - start_s) / period_s
        est.update(
            EstimatorInputs(
                fault_counts={"exception": count}, ckpt_cost_s=ckpt_cost_s
            ),
            now=t,
        )
        t += tick_s
    return count


class TestEstimator:
    def test_mtbf_inf_until_first_fault(self):
        est = GoodputEstimator(window_s=100.0)
        est.update(EstimatorInputs(fault_counts={"exception": 0.0}), now=0.0)
        est.update(EstimatorInputs(fault_counts={"exception": 0.0}), now=50.0)
        assert math.isinf(est.mtbf_s())
        assert math.isinf(est.tau_opt())

    def test_quiet_after_faults_relaxes_to_window_bound(self):
        """Once faults HAVE been seen, a quiet window reads as
        ``MTBF >= window_s`` so cadence relaxes instead of pinning at the
        last noisy measurement."""
        est = GoodputEstimator(window_s=100.0)
        _feed_constant_rate(est, 0.0, 200.0, period_s=10.0)
        assert est.mtbf_s() == pytest.approx(10.0, rel=0.3)
        # regime calms: counts stop growing for > window
        count = 20.0
        for t in range(200, 400, 5):
            est.update(
                EstimatorInputs(fault_counts={"exception": count}),
                now=float(t),
            )
        assert est.mtbf_s() == pytest.approx(100.0)  # the window lower bound
        assert not math.isinf(est.tau_opt())

    def test_cadence_tracks_young_daly_after_rate_step(self):
        """Acceptance: after a fault-rate step the Young/Daly target moves
        to the new optimum sqrt(2·C·MTBF)."""
        est = GoodputEstimator(window_s=100.0)
        # phase 1: one fault per 5s, measured save cost 2s
        _feed_constant_rate(est, 0.0, 300.0, period_s=5.0, ckpt_cost_s=2.0)
        tau1 = est.tau_opt()
        assert tau1 == pytest.approx(young_daly_interval(2.0, 5.0), rel=0.25)
        # phase 2 (the step): one fault per 50s, cumulative count continues
        _feed_constant_rate(
            est, 300.0, 800.0, period_s=50.0, count0=60.0, ckpt_cost_s=2.0
        )
        tau2 = est.tau_opt()
        assert tau2 == pytest.approx(young_daly_interval(2.0, 50.0), rel=0.25)
        assert tau2 > tau1 * 2  # the optimum clearly moved with the regime

    def test_costs_ewma_and_defaults(self):
        est = GoodputEstimator(window_s=100.0)
        c0, r0 = est.costs()
        assert c0 == 5.0 and r0 == 30.0  # documented defaults
        est.update(
            EstimatorInputs(ckpt_cost_s=10.0, recovery_cost_s=20.0), now=0.0
        )
        est.update(
            EstimatorInputs(ckpt_cost_s=20.0, recovery_cost_s=40.0), now=10.0
        )
        c, r = est.costs()
        assert 10.0 < c < 20.0  # EWMA between the samples
        assert 20.0 < r < 40.0

    def test_expected_goodput_peaks_near_tau_opt(self):
        est = GoodputEstimator(window_s=1000.0)
        _feed_constant_rate(
            est, 0.0, 1000.0, period_s=100.0, tick_s=20.0, ckpt_cost_s=5.0
        )
        tau = est.tau_opt()
        assert est.expected_goodput(tau) > est.expected_goodput(tau / 5)
        assert est.expected_goodput(tau) > est.expected_goodput(tau * 5)

    def test_telemetry_feed_maps_registry_series(self):
        reg = Registry(enabled=True)
        reg.counter(
            "tpurx_inprocess_interruptions_total", labels=("kind",)
        ).labels(kind="exception").inc(3)
        reg.counter("tpurx_monitor_trips_total").inc(2)
        reg.counter("tpurx_collective_timeouts_total").inc(1)
        reg.gauge("tpurx_health_score", labels=("check",)).labels(
            check="kernel_log"
        ).set(0.75)
        reg.counter("tpurx_kmsg_faults_total", labels=("class",)).labels(
            "hard"
        ).inc(4)
        inputs = TelemetryFeed(registry=reg).collect()
        assert inputs.fault_counts["exception"] == 3
        assert inputs.fault_counts["hang"] == 2
        assert inputs.fault_counts["collective"] == 1
        assert inputs.node_risk == 0.75
        assert inputs.kmsg_hard_total == 4


# ---- actuator ---------------------------------------------------------------


class TestActuator:
    def test_cadence_clamped_and_hysteresis_damped(self):
        act = Actuator()
        lo = env.POLICY_CADENCE_MIN_S.get()
        hi = env.POLICY_CADENCE_MAX_S.get()
        a = act.set_cadence(lo / 100.0, "clamp low")
        assert a is not None and float(a.value) == pytest.approx(lo)
        a = act.set_cadence(hi * 100.0, "clamp high")
        assert float(a.value) == pytest.approx(hi)
        # < hysteresis-pct relative change from the current value: damped
        assert act.set_cadence(hi * 0.99, "noise") is None
        assert env.CKPT_INTERVAL_S.get() == pytest.approx(hi)

    def test_infinite_target_relaxes_to_max(self):
        act = Actuator()
        a = act.set_cadence(math.inf, "no faults ever")
        assert float(a.value) == pytest.approx(env.POLICY_CADENCE_MAX_S.get())

    def test_replication_bounds_and_clear(self):
        act = Actuator(max_replication=4)
        a = act.set_replication(9, "cap")
        assert a.value == "4"
        assert env.LCKPT_REPLICATION.get() == 4
        assert act.set_replication(4, "same") is None  # no-op damped
        a = act.set_replication(None, "clear")
        assert a.value == ""
        assert env.LCKPT_REPLICATION.get() is None
        assert act.set_replication(None, "already clear") is None

    def test_delta_flip_and_clear(self):
        act = Actuator()
        a = act.set_delta(True, "risk")
        assert a is not None and env.CKPT_DELTA.get() is True
        assert act.set_delta(True, "again") is None
        a = act.set_delta(None, "cleared")
        assert a.value == ""
        assert env.runtime_overrides().get(env.CKPT_DELTA.name) is None

    def test_start_rung_arms_ledger_and_shrink_stage(self):
        act = Actuator()
        a = act.set_start_rung("hang", "mesh_shrink", "ledger pick")
        assert a.target == "ledger:hang" and a.value == "mesh_shrink"
        assert ledger().start_rung("hang") == "mesh_shrink"
        assert env.SHRINK_MESH.get()  # the opt-in stage got enabled
        assert act.set_start_rung("hang", "mesh_shrink", "same") is None
        with pytest.raises(ValueError):
            act.set_start_rung("hang", "warp_drive", "nope")

    def test_degrade_ladder_compositions(self):
        act = Actuator()
        a = act.set_degrade_ladder("skip_retry", "timeouts escalate")
        assert a.value == "relayout,shrink"
        assert env.COLL_DEGRADE.get() == "relayout,shrink"
        assert act.set_degrade_ladder("skip_retry", "same") is None
        with pytest.raises(ValueError):
            act.set_degrade_ladder("yolo", "nope")

    def test_apply_replays_remote_actions(self):
        """The per-rank path: a published Action re-applies verbatim —
        set, clear, and ledger arms — without re-deciding."""
        act = Actuator()
        act.apply(Action("set_cadence", env.CKPT_INTERVAL_S.name, "42.0", "r"))
        assert env.CKPT_INTERVAL_S.get() == pytest.approx(42.0)
        act.apply(Action("set_cadence", env.CKPT_INTERVAL_S.name, "", "clear"))
        assert env.runtime_overrides().get(env.CKPT_INTERVAL_S.name) is None
        act.apply(Action("set_start_rung", "ledger:hang", "in_job", "r"))
        assert ledger().start_rung("hang") == "in_job"

    def test_undeclared_knob_rejected(self):
        with pytest.raises(KeyError):
            env.set_runtime_override("TPURX_NOT_A_KNOB", "1")


# ---- rung ledger ------------------------------------------------------------


class TestRungLedger:
    def test_empty_ledger_starts_at_top(self):
        led = RungLedger()
        assert led.pick_start_rung("hang") == "in_process"

    def test_escalating_class_skips_dead_rungs(self):
        """A class whose in-process rung always fails and whose in-job
        rung always recovers should start at in_job once enough episodes
        are recorded."""
        led = RungLedger()
        for _ in range(4):
            led.record("hang", "in_process", False, 10.0)
            led.record("hang", "mesh_shrink", False, 30.0)
            led.record("hang", "in_job", True, 60.0)
        assert led.pick_start_rung("hang") == "in_job"
        assert led.expected_cost("hang", "in_job") < led.expected_cost(
            "hang", "in_process"
        )

    def test_reliable_class_stays_at_top(self):
        led = RungLedger()
        for _ in range(5):
            led.record("exception", "in_process", True, 4.0)
        assert led.pick_start_rung("exception") == "in_process"

    def test_armed_rung_wins_over_pick(self):
        led = RungLedger()
        for _ in range(5):
            led.record("exception", "in_process", True, 4.0)
        led.arm("exception", "in_job", "operator override")
        assert led.start_rung("exception") == "in_job"
        led.disarm("exception")
        assert led.start_rung("exception") == "in_process"

    def test_ledger_accounting_vs_abort_ladder_counters(self, store_server):
        """Satellite cross-check: one real in-process restart episode must
        appear BOTH in the abort ladder's own run counter and as exactly
        one successful in_process episode in the policy ledger."""
        from tpu_resiliency.inprocess import Wrapper
        from tpu_resiliency.store import StoreClient

        reg = get_registry()
        runs_before = reg.value_of("tpurx_abort_ladder_runs_total")

        def factory():
            return StoreClient(
                "127.0.0.1", store_server.port, timeout=10.0
            )

        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected fault")
            return "recovered"

        os.environ["TPURX_RANK"] = "0"
        os.environ["TPURX_WORLD_SIZE"] = "1"
        try:
            w = Wrapper(
                store_factory=factory,
                group="policy-ledger",
                enable_monitor_process=False,
                enable_sibling_monitor=False,
            )
            assert w(flaky)() == "recovered"
        finally:
            os.environ.pop("TPURX_RANK", None)
            os.environ.pop("TPURX_WORLD_SIZE", None)
        st = ledger().stats("exception", "in_process")
        assert st.attempts == 1 and st.successes == 1
        assert st.total_cost_s > 0.0  # measured recovery time, not a stub
        runs_after = reg.value_of("tpurx_abort_ladder_runs_total")
        # one restart episode <=> one abort-ladder execution
        assert runs_after - runs_before == st.attempts


# ---- controller loop --------------------------------------------------------


class _ScriptedFeed:
    """A feed whose collect() replays a scripted inputs sequence (the last
    entry repeats once the script runs out)."""

    def __init__(self, script):
        self.script = list(script)
        self.i = 0

    def collect(self):
        inputs = self.script[min(self.i, len(self.script) - 1)]
        self.i += 1
        return inputs


class _FakeStore:
    def __init__(self):
        self.data = {}

    def set(self, key, value):
        self.data[key] = value

    def delete(self, key):
        self.data.pop(key, None)

    def try_get(self, key):
        return self.data.get(key)


def _exception_script(period_s, ticks, tick_s, count0=0.0, ckpt_cost_s=2.0):
    return [
        EstimatorInputs(
            fault_counts={"exception": count0 + i * tick_s / period_s},
            ckpt_cost_s=ckpt_cost_s,
        )
        for i in range(ticks)
    ]


class TestPolicyController:
    def test_no_cadence_action_before_any_fault(self):
        ctl = PolicyController(
            feed=_ScriptedFeed([EstimatorInputs()]),
            estimator=GoodputEstimator(window_s=100.0),
        )
        actions = ctl.tick(now=0.0)
        assert not any(a.kind == "set_cadence" for a in actions)
        assert env.runtime_overrides().get(env.CKPT_INTERVAL_S.name) is None

    def test_cadence_converges_to_young_daly_after_rate_step(self):
        """Acceptance: drive the controller with a synthetic feed whose
        fault rate steps down; the applied cadence must first sit at the
        noisy-phase Young/Daly optimum, then relax toward the quiet one."""
        window = 100.0
        script = _exception_script(period_s=5.0, ticks=40, tick_s=5.0)
        script += _exception_script(
            period_s=50.0, ticks=100, tick_s=5.0, count0=40.0
        )
        ctl = PolicyController(
            feed=_ScriptedFeed(script),
            estimator=GoodputEstimator(window_s=window),
        )
        t = 0.0
        cadences = []
        for _ in range(140):
            ctl.tick(now=t)
            cadences.append(env.CKPT_INTERVAL_S.get())
            t += 5.0
        lo = env.POLICY_CADENCE_MIN_S.get()
        noisy_opt = max(lo, young_daly_interval(2.0, 5.0))
        quiet_opt = young_daly_interval(2.0, 50.0)
        assert cadences[39] == pytest.approx(noisy_opt, rel=0.3)
        assert cadences[-1] == pytest.approx(quiet_opt, rel=0.3)
        assert cadences[-1] > cadences[39]

    def test_decisions_journaled_and_counted(self):
        store = _FakeStore()
        reg = get_registry()
        before = reg.value_of(
            "tpurx_policy_decisions_total", {"action": "set_cadence"}
        )
        ctl = PolicyController(
            feed=_ScriptedFeed(
                _exception_script(period_s=5.0, ticks=30, tick_s=5.0)
            ),
            estimator=GoodputEstimator(window_s=100.0),
            store=store,
        )
        t = 0.0
        for _ in range(30):
            ctl.tick(now=t)
            t += 5.0
        assert ctl.journal, "applied actions must be journaled"
        after = reg.value_of(
            "tpurx_policy_decisions_total", {"action": "set_cadence"}
        )
        assert after - before >= 1
        # every journal record landed in the store, and latest parses back
        for rec in ctl.journal:
            assert store.try_get(f"policy/journal/{rec['seq']}") is not None
        seq, actions = decisions_from_json(store.try_get(K_DECISION_LATEST))
        assert seq == ctl.seq and actions

    def test_journal_keys_are_garbage_collected(self):
        store = _FakeStore()
        ctl = PolicyController(
            feed=_ScriptedFeed(
                _exception_script(period_s=2.0, ticks=200, tick_s=5.0)
            ),
            estimator=GoodputEstimator(window_s=50.0),
            store=store,
            journal_keep=4,
        )
        # force a fresh decision every tick: disable hysteresis damping
        env.set_runtime_override(env.POLICY_HYSTERESIS_PCT.name, "0")
        t = 0.0
        for _ in range(60):
            ctl.tick(now=t)
            t += 5.0
        assert ctl.seq > 8
        journal_keys = [
            k for k in store.data if k.startswith("policy/journal/")
        ]
        assert len(journal_keys) <= 4 + 1  # keep window (+latest in flight)
        assert f"policy/journal/{ctl.seq}" in store.data
        assert "policy/journal/1" not in store.data

    def test_risk_arms_replication_and_delta_then_relaxes(self):
        threshold = env.POLICY_RISK_THRESHOLD.get()
        risky = EstimatorInputs(
            fault_counts={"exception": 1.0}, node_risk=threshold + 0.2
        )
        calm = EstimatorInputs(fault_counts={"exception": 1.0}, node_risk=0.0)
        ctl = PolicyController(
            feed=_ScriptedFeed([risky, risky, calm, calm]),
            estimator=GoodputEstimator(window_s=100.0),
        )
        ctl.tick(now=0.0)
        ctl.tick(now=5.0)
        assert env.LCKPT_REPLICATION.get() == 3
        assert env.CKPT_DELTA.get() is True
        ctl.tick(now=10.0)
        ctl.tick(now=15.0)
        assert env.LCKPT_REPLICATION.get() is None  # override cleared
        assert env.runtime_overrides().get(env.CKPT_DELTA.name) is None

    def test_rung_decision_follows_ledger(self):
        for _ in range(4):
            ledger().record("exception", "in_process", False, 10.0)
            ledger().record("exception", "mesh_shrink", False, 30.0)
            ledger().record("exception", "in_job", True, 60.0)
        ctl = PolicyController(
            feed=_ScriptedFeed(
                _exception_script(period_s=5.0, ticks=10, tick_s=5.0)
            ),
            estimator=GoodputEstimator(window_s=100.0),
        )
        t = 0.0
        actions = []
        for _ in range(10):
            actions += ctl.tick(now=t)
            t += 5.0
        rung_actions = [a for a in actions if a.kind == "set_start_rung"]
        assert rung_actions and rung_actions[-1].value == "in_job"
        assert ledger().start_rung("exception") == "in_job"


# ---- per-rank client --------------------------------------------------------


class TestPolicyClient:
    def test_poll_applies_published_batch_once(self):
        from tpu_resiliency.fault_tolerance.control_plane import PolicyClient

        store = _FakeStore()
        ctl = PolicyController(
            feed=_ScriptedFeed(
                _exception_script(period_s=5.0, ticks=30, tick_s=5.0)
            ),
            estimator=GoodputEstimator(window_s=100.0),
            store=store,
        )
        t = 0.0
        for _ in range(30):
            ctl.tick(now=t)
            t += 5.0
        published_cadence = env.CKPT_INTERVAL_S.get()
        assert published_cadence is not None
        # a "different rank": overrides wiped, then the client re-applies
        env.clear_runtime_overrides()
        assert env.CKPT_INTERVAL_S.get() is None
        client = PolicyClient(store, poll_interval_s=3600.0)
        assert client.poll_once() > 0
        assert env.CKPT_INTERVAL_S.get() == pytest.approx(published_cadence)
        assert client.poll_once() == 0  # same seq: idempotent

    def test_empty_store_is_a_noop(self):
        from tpu_resiliency.fault_tolerance.control_plane import PolicyClient

        client = PolicyClient(_FakeStore(), poll_interval_s=3600.0)
        assert client.poll_once() == 0


# ---- health gauges (fault injection) ---------------------------------------


class TestHealthGauges:
    def test_kmsg_injection_raises_score_and_counter(self, tmp_path):
        from tpu_resiliency.health.kmsg import KernelLogHealthCheck

        reg = get_registry()
        hard_before = reg.value_of(
            "tpurx_kmsg_faults_total", {"class": "hard"}
        )
        log = tmp_path / "kern.log"
        log.write_text("")
        chk = KernelLogHealthCheck(
            source=str(log), window_s=60.0, threshold=2
        )
        assert chk.run().healthy  # attach + baseline on the empty log
        with log.open("a") as f:
            f.write("tpu0: device error, link reset requested\n")
        result = chk.run()
        assert result.healthy  # 1 hard line < threshold 2
        hard_after = reg.value_of(
            "tpurx_kmsg_faults_total", {"class": "hard"}
        )
        assert hard_after - hard_before == 1
        assert reg.value_of(
            "tpurx_health_score", {"check": "kernel_log"}
        ) == pytest.approx(0.5)  # 1 of threshold 2
        # a second hard line crosses the threshold -> unhealthy, score 1.0
        with log.open("a") as f:
            f.write("EDAC MC0: UE page fault\n")
        assert not chk.run().healthy
        assert reg.value_of(
            "tpurx_health_score", {"check": "kernel_log"}
        ) == pytest.approx(1.0)

    def test_health_score_feeds_estimator_risk(self):
        reg = Registry(enabled=True)
        reg.gauge("tpurx_health_score", labels=("check",)).labels(
            check="kernel_log"
        ).set(0.9)
        est = GoodputEstimator(window_s=100.0)
        est.update(TelemetryFeed(registry=reg).collect(), now=0.0)
        assert est.node_risk == pytest.approx(0.9)
