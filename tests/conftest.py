"""Test configuration.

All tests run on CPU with 8 virtual XLA devices so multi-chip sharding logic
is exercised without TPU hardware (mirrors the reference's strategy of
CPU/gloo multiprocess tests, SURVEY.md §4).  Env must be set before jax
import — conftest runs first, and worker subprocesses inherit it.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize force-selects the TPU platform via jax.config, which
# overrides the env var — override it back before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def store_server():
    from tpu_resiliency.store import StoreServer

    server = StoreServer(host="127.0.0.1", port=0).start_in_thread()
    yield server
    server.stop()


@pytest.fixture
def native_store_server():
    from tpu_resiliency.store.native import NativeStoreServer

    server = NativeStoreServer(host="127.0.0.1", port=0).start()
    yield server
    server.stop()


@pytest.fixture
def store(store_server):
    from tpu_resiliency.store import StoreClient

    client = StoreClient("127.0.0.1", store_server.port, timeout=10.0)
    yield client
    client.close()
