"""Warm restore ladder tests: shm-resident read source, digest-keyed delta
saves, and the local manager's peer-memory rung.

The resident registry (``async_ckpt/resident.py``) promotes the staging
pool's committed generation to a read source; ``load_checkpoint`` must
restore a complete generation without opening ANY checkpoint file.  Delta
saves skip draining chunks whose crc matches the previous committed
generation and record provenance so a cold restore of the delta directory
still covers every byte.  The local manager's ladder tries its own resident
blob, then clique peers' resident copies over the TCP exchange, then disk.
"""

import json
import os
import shutil
import threading

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_resiliency.checkpointing.async_ckpt import resident as resident_mod
from tpu_resiliency.checkpointing.async_ckpt import checkpointer as ckpt_mod
from tpu_resiliency.checkpointing.async_ckpt import writer as writer_mod
from tpu_resiliency.checkpointing.async_ckpt.checkpointer import (
    AsyncCheckpointer,
    load_checkpoint,
)
from tpu_resiliency.checkpointing.local.manager import LocalCheckpointManager
from tpu_resiliency.checkpointing.local.replication import (
    CliqueReplication,
    PeerExchange,
)
from tpu_resiliency.store import StoreClient
from tpu_resiliency.telemetry import get_registry


def _source_bytes(source):
    return get_registry().value_of(
        "tpurx_ckpt_restore_source_total", {"source": source}
    )


def make_tree(seed=0, step=1):
    k = jax.random.PRNGKey(seed)
    return {
        "w": jax.device_put(jax.random.normal(k, (64, 32))),
        "b": jax.device_put(np.arange(256, dtype=np.float32)),
        "step": np.int64(step),
    }


def assert_trees_equal(a, b):
    la, _ = jax.tree_util.tree_flatten(a)
    lb, _ = jax.tree_util.tree_flatten(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.fixture(autouse=True)
def _fresh_registry():
    resident_mod.invalidate()
    yield
    resident_mod.invalidate()


def _forbid_file_reads(monkeypatch):
    def _boom(*_a, **_k):
        raise AssertionError("warm restore touched a checkpoint file")

    monkeypatch.setattr(writer_mod, "ChunkReader", _boom)
    monkeypatch.setattr(ckpt_mod, "read_metadata", _boom)
    monkeypatch.setattr(ckpt_mod, "is_committed", _boom)


class TestResidentRestore:
    def test_warm_restore_no_file_opens(self, tmp_path, monkeypatch):
        """In-process-restart smoke: after close(), a complete resident
        generation satisfies the whole restore from memory — metadata
        included — with every chunk verified against the committed index."""
        tree = make_tree(1)
        d = str(tmp_path / "ck")
        cp = AsyncCheckpointer(digest=True, resident=True)
        try:
            cp.save(tree, d, extra_metadata={"iteration": 1})
        finally:
            cp.close()  # the resident generation outlives the checkpointer
        rc = resident_mod.lookup(d)
        assert rc is not None and rc.complete
        _forbid_file_reads(monkeypatch)
        stats = {}
        restored = load_checkpoint(d, tree, threads=2, stats=stats)
        assert_trees_equal(tree, restored)
        assert stats["bytes_shm"] > 0
        assert stats["bytes_shm"] == stats["bytes_read"]  # 100% warm

    def test_resident_opt_out_reads_disk(self, tmp_path):
        tree = make_tree(2)
        d = str(tmp_path / "ck")
        cp = AsyncCheckpointer(digest=True, resident=True)
        try:
            cp.save(tree, d, extra_metadata={"iteration": 1})
        finally:
            cp.close()
        stats = {}
        restored = load_checkpoint(d, tree, stats=stats, resident=False)
        assert_trees_equal(tree, restored)
        assert stats["bytes_shm"] == 0

    def test_serial_path_ignores_resident(self, tmp_path):
        tree = make_tree(3)
        d = str(tmp_path / "ck")
        cp = AsyncCheckpointer(digest=True, resident=True)
        try:
            cp.save(tree, d, extra_metadata={"iteration": 1})
        finally:
            cp.close()
        assert resident_mod.lookup(d) is not None
        restored = load_checkpoint(d, tree, serial=True)
        assert_trees_equal(tree, restored)

    def test_sharded_leaves_warm_and_cold(self, tmp_path):
        """Row sharding exercises the direct-into-leaf-buffer path, column
        sharding the scratch-then-place path — both must restore equal from
        the shm source AND from disk after invalidation."""
        devs = jax.devices()
        assert len(devs) == 8
        mesh = Mesh(np.array(devs), ("x",))
        rows = jax.device_put(
            np.arange(64 * 32, dtype=np.float32).reshape(64, 32),
            NamedSharding(mesh, P("x", None)),
        )
        cols = jax.device_put(
            np.arange(16 * 64, dtype=np.float32).reshape(16, 64),
            NamedSharding(mesh, P(None, "x")),
        )
        tree = {"rows": rows, "cols": cols, "step": np.int64(4)}
        d = str(tmp_path / "ck")
        cp = AsyncCheckpointer(digest=True, resident=True)
        try:
            cp.save(tree, d, extra_metadata={"iteration": 1})
        finally:
            cp.close()
        stats = {}
        warm = load_checkpoint(d, tree, threads=2, stats=stats)
        assert stats["bytes_shm"] == stats["bytes_read"] > 0
        assert_trees_equal(tree, warm)
        assert warm["rows"].sharding.is_equivalent_to(rows.sharding, 2)
        assert warm["cols"].sharding.is_equivalent_to(cols.sharding, 2)
        resident_mod.invalidate(d)
        stats = {}
        cold = load_checkpoint(d, tree, threads=2, stats=stats)
        assert stats["bytes_shm"] == 0
        assert_trees_equal(tree, cold)

    def test_layout_change_invalidates_resident(self, tmp_path):
        cp = AsyncCheckpointer(digest=True, resident=True)
        d1, d2 = str(tmp_path / "c1"), str(tmp_path / "c2")
        try:
            cp.save(make_tree(5), d1, extra_metadata={"iteration": 1})
            assert resident_mod.lookup(d1) is not None
            # different leaf set = different plan signature: the staging
            # pool re-shapes, so the old generation must be evicted
            other = {"v": jax.device_put(np.ones((8, 8), dtype=np.float32))}
            cp.save(other, d2, extra_metadata={"iteration": 2})
        finally:
            cp.close()
        assert resident_mod.lookup(d1) is None
        assert resident_mod.lookup(d2) is not None


class TestDeltaSaves:
    def test_delta_skips_frozen_chunks_and_restores(self, tmp_path):
        """Save, mutate ONE leaf, delta-save: frozen chunks are recorded by
        provenance (no drain) and both warm and cold restores of the delta
        directory cover every byte."""
        cp = AsyncCheckpointer(digest=True, resident=True, delta=True)
        d1, d2 = str(tmp_path / "c1"), str(tmp_path / "c2")
        t1 = make_tree(6, step=1)
        t2 = dict(t1, step=np.int64(2))  # w and b frozen
        try:
            cp.save(t1, d1, extra_metadata={"iteration": 1})
            cp.save(t2, d2, extra_metadata={"iteration": 2})
        finally:
            cp.close()
        with open(os.path.join(d2, f"process_{cp.process_index}.json")) as f:
            idx = json.load(f)
        based = [
            c
            for s in idx["shards"]
            for c in s.get("chunks", [])
            if len(c) > 3
        ]
        assert based, "delta save recorded no provenance chunks"
        assert any(
            os.path.abspath(d1) in b
            for s in idx["shards"]
            for b in s.get("bases", [])
        )
        # warm restore of the delta generation (resident covers it fully)
        stats = {}
        warm = load_checkpoint(d2, t2, threads=2, stats=stats)
        assert stats["bytes_shm"] == stats["bytes_read"]
        assert_trees_equal(t2, warm)
        # cold restores must resolve provenance across generation dirs
        resident_mod.invalidate()
        assert_trees_equal(t2, load_checkpoint(d2, t2, threads=2))
        assert_trees_equal(t2, load_checkpoint(d2, t2, serial=True))

    def test_delta_then_layout_change_invalidates(self, tmp_path):
        """Delta chain then a layout change: the resident generation of the
        old layout is gone and the new layout restores clean."""
        cp = AsyncCheckpointer(digest=True, resident=True, delta=True)
        d1, d2, d3 = (str(tmp_path / n) for n in ("c1", "c2", "c3"))
        t1 = make_tree(7, step=1)
        t2 = dict(t1, step=np.int64(2))
        other = {"v": jax.device_put(np.full((16,), 3.0, dtype=np.float32))}
        try:
            cp.save(t1, d1, extra_metadata={"iteration": 1})
            cp.save(t2, d2, extra_metadata={"iteration": 2})
            assert resident_mod.lookup(d2) is not None
            cp.save(other, d3, extra_metadata={"iteration": 3})
        finally:
            cp.close()
        assert resident_mod.lookup(d1) is None
        assert resident_mod.lookup(d2) is None
        rc = resident_mod.lookup(d3)
        assert rc is not None
        assert_trees_equal(other, load_checkpoint(d3, other, threads=2))
        # the delta dir still restores from disk (provenance, not memory)
        assert_trees_equal(t2, load_checkpoint(d2, t2, threads=2))


# -- peer-memory rung --------------------------------------------------------


def _run_ranks(world, fn):
    errors, results = [], {}

    def wrap(rank):
        try:
            results[rank] = fn(rank)
        except Exception as exc:  # noqa: BLE001
            import traceback

            traceback.print_exc()
            errors.append((rank, exc))

    threads = [threading.Thread(target=wrap, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    return results


def _mgr_tree(rank):
    return {
        "w": np.arange(4096, dtype=np.float32) + rank,
        "rank_marker": np.array([rank], dtype=np.int32),
    }


def test_peer_memory_restore(store_server, tmp_path):
    """Rank 1 loses its disk AND its own resident copy; the ladder serves it
    from rank 0's memory-resident replica over the exchange, then persists a
    durable copy."""
    world = 2
    peer_before = _source_bytes("peer_memory")

    def member(rank):
        store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
        ex = PeerExchange(store, rank, namespace="pxwm1")
        repl = CliqueReplication(ex, world, replication_factor=2)
        mgr = LocalCheckpointManager(
            str(tmp_path / f"node{rank}"), rank, world,
            store=store, replication=repl,
        )
        try:
            mgr.save(_mgr_tree(rank), iteration=7, is_async=False)
            if rank == 1:
                mgr.drop_resident()
                shutil.rmtree(mgr.root)
            tree, it = mgr.load(_mgr_tree(rank), iteration=7)
            if rank == 1:
                # durability repaired: the warm fetch left a disk copy
                path = mgr._blob_path(7, 1)
                assert os.path.exists(path) and os.path.exists(path + ".done")
            return int(np.asarray(tree["rank_marker"])[0])
        finally:
            mgr.close()
            ex.close()
            store.close()

    results = _run_ranks(world, member)
    assert results == {0: 0, 1: 1}
    assert _source_bytes("peer_memory") > peer_before
    assert _source_bytes("local_resident") > 0


def test_peer_memory_stall_falls_to_disk(store_server, tmp_path, monkeypatch):
    """A stalled serving peer (drops requests) must NOT wedge the restore:
    the rung times out and the ladder falls through to the rank's own disk
    blob with fallback depth 0."""
    monkeypatch.setenv("TPURX_FAULT", "peer_mem_stall")
    monkeypatch.setenv("TPURX_FAULT_RANKS", "0")  # only rank 0 drops requests
    monkeypatch.setenv("TPURX_CKPT_PEER_MEM_TIMEOUT", "1.5")
    world = 2
    disk_before = _source_bytes("local_disk")
    peer_before = _source_bytes("peer_memory")

    def member(rank):
        store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
        ex = PeerExchange(store, rank, namespace="pxwm2")
        repl = CliqueReplication(ex, world, replication_factor=2)
        mgr = LocalCheckpointManager(
            str(tmp_path / f"node{rank}"), rank, world,
            store=store, replication=repl,
        )
        try:
            mgr.save(_mgr_tree(rank), iteration=9, is_async=False)
            if rank == 1:
                mgr.drop_resident()  # forces the ladder past the memory rung
            tree, _ = mgr.load(_mgr_tree(rank), iteration=9)
            return int(np.asarray(tree["rank_marker"])[0])
        finally:
            mgr.close()
            ex.close()
            store.close()

    results = _run_ranks(world, member)
    assert results == {0: 0, 1: 1}
    assert _source_bytes("peer_memory") == peer_before  # rung never served
    assert _source_bytes("local_disk") > disk_before
    assert get_registry().value_of("tpurx_ckpt_fallback_depth") == 0
