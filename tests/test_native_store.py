"""Native (C++) store server: protocol conformance + barrier + perf sanity.

Conformance reuses the semantics covered in test_store.py, executed against
the epoll C++ server — one protocol, two implementations.
"""

import threading
import time

import pytest

from tpu_resiliency.store import (
    BarrierOverflow,
    StoreClient,
    StoreTimeout,
    barrier,
    reentrant_barrier,
)


@pytest.fixture
def nstore(native_store_server):
    c = StoreClient("127.0.0.1", native_store_server.port, timeout=10.0)
    yield c
    c.close()


def test_basic_ops(nstore):
    nstore.set("k", b"v")
    assert nstore.get("k") == b"v"
    assert nstore.try_get("missing") is None
    assert nstore.add("ctr", 5) == 5
    assert nstore.add("ctr", -2) == 3
    assert nstore.append("log", b"ab") == 2
    assert nstore.append("log", b"c") == 3
    assert nstore.get("log") == b"abc"
    assert nstore.delete("log") is True
    assert nstore.delete("log") is False
    assert nstore.num_keys() == 2
    assert nstore.ping()


def test_cas(nstore):
    assert nstore.compare_set("c", b"", b"v1") == b"v1"
    assert nstore.compare_set("c", b"bad", b"v2") == b"v1"
    assert nstore.compare_set("c", b"v1", b"v2") == b"v2"


def test_blocking_get_and_wait(nstore, native_store_server):
    def setter():
        time.sleep(0.15)
        c = StoreClient("127.0.0.1", native_store_server.port)
        c.set("late", b"x")
        c.set("late2", b"y")
        c.close()

    t = threading.Thread(target=setter)
    t.start()
    assert nstore.get("late", timeout=5.0) == b"x"
    nstore.wait(["late", "late2"], timeout=5.0)
    t.join()
    with pytest.raises(StoreTimeout):
        nstore.get("never", timeout=0.2)
    with pytest.raises(StoreTimeout):
        nstore.wait(["never"], timeout=0.2)


def test_multi_and_list(nstore):
    nstore.multi_set({"p/a": b"1", "p/b": b"2", "q/c": b"3"})
    assert sorted(nstore.list_keys("p/")) == [b"p/a", b"p/b"]
    assert nstore.multi_get(["p/a", "q/c"]) == [b"1", b"3"]
    assert nstore.multi_get(["p/a", "nope"]) is None
    assert nstore.check(["p/a", "p/b"]) is True
    assert nstore.check(["p/a", "zz"]) is False


def test_concurrent_add_atomicity(native_store_server):
    n_threads, n_incr = 8, 100

    def worker():
        c = StoreClient("127.0.0.1", native_store_server.port)
        for _ in range(n_incr):
            c.add("counter", 1)
        c.close()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    c = StoreClient("127.0.0.1", native_store_server.port)
    assert c.add("counter", 0) == n_threads * n_incr
    c.close()


def test_barriers_on_native(native_store_server):
    world = 4
    errors = []

    def member(i):
        try:
            c = StoreClient("127.0.0.1", native_store_server.port)
            barrier(c, "nb", world, timeout=10.0)
            reentrant_barrier(c, "nrb", i, world, timeout=10.0)
            if i == 0:
                reentrant_barrier(c, "nrb", i, world, timeout=10.0)
            c.close()
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=member, args=(i,)) for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors


def test_garbage_opcode_drops_conn_server_survives(nstore, native_store_server):
    import socket

    s = socket.create_connection(("127.0.0.1", native_store_server.port))
    s.sendall(b"\xff\x00\x00\x00\x00garbage")
    time.sleep(0.1)
    s.close()
    nstore.set("after", b"ok")
    assert nstore.get("after") == b"ok"


def test_native_faster_than_python_roundtrips(native_store_server, store_server):
    """Throughput sanity: the native server should beat asyncio on small-op
    roundtrips (not asserted strictly — just recorded + a sanity floor)."""

    def bench(port, n=2000):
        c = StoreClient("127.0.0.1", port)
        t0 = time.perf_counter()
        for i in range(n):
            c.add("bench", 1)
        dt = time.perf_counter() - t0
        c.close()
        return n / dt

    native_ops = bench(native_store_server.port)
    python_ops = bench(store_server.port)
    print(f"\nnative: {native_ops:,.0f} ops/s, asyncio: {python_ops:,.0f} ops/s, "
          f"speedup {native_ops / python_ops:.2f}x")
    assert native_ops > 2000  # sanity floor for a local roundtrip
