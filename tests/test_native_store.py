"""Native (C++) store server: protocol conformance + barrier + perf sanity.

Conformance reuses the semantics covered in test_store.py, executed against
the epoll C++ server — one protocol, two implementations.
"""

import threading
import time

import pytest

from tpu_resiliency.store import (
    BarrierOverflow,
    StoreClient,
    StoreTimeout,
    barrier,
    reentrant_barrier,
)


@pytest.fixture
def nstore(native_store_server):
    c = StoreClient("127.0.0.1", native_store_server.port, timeout=10.0)
    yield c
    c.close()


def test_basic_ops(nstore):
    nstore.set("k", b"v")
    assert nstore.get("k") == b"v"
    assert nstore.try_get("missing") is None
    assert nstore.add("ctr", 5) == 5
    assert nstore.add("ctr", -2) == 3
    assert nstore.append("log", b"ab") == 2
    assert nstore.append("log", b"c") == 3
    assert nstore.get("log") == b"abc"
    assert nstore.delete("log") is True
    assert nstore.delete("log") is False
    assert nstore.num_keys() == 2
    assert nstore.ping()


def test_cas(nstore):
    assert nstore.compare_set("c", b"", b"v1") == b"v1"
    assert nstore.compare_set("c", b"bad", b"v2") == b"v1"
    assert nstore.compare_set("c", b"v1", b"v2") == b"v2"


def test_blocking_get_and_wait(nstore, native_store_server):
    def setter():
        time.sleep(0.15)
        c = StoreClient("127.0.0.1", native_store_server.port)
        c.set("late", b"x")
        c.set("late2", b"y")
        c.close()

    t = threading.Thread(target=setter)
    t.start()
    assert nstore.get("late", timeout=5.0) == b"x"
    nstore.wait(["late", "late2"], timeout=5.0)
    t.join()
    with pytest.raises(StoreTimeout):
        nstore.get("never", timeout=0.2)
    with pytest.raises(StoreTimeout):
        nstore.wait(["never"], timeout=0.2)


def test_multi_and_list(nstore):
    nstore.multi_set({"p/a": b"1", "p/b": b"2", "q/c": b"3"})
    assert sorted(nstore.list_keys("p/")) == [b"p/a", b"p/b"]
    assert nstore.multi_get(["p/a", "q/c"]) == [b"1", b"3"]
    # per-key miss semantics (matches the asyncio server): absent keys are
    # None entries, present ones keep their values
    assert nstore.multi_get(["p/a", "nope"]) == [b"1", None]
    assert nstore.check(["p/a", "p/b"]) is True
    assert nstore.check(["p/a", "zz"]) is False


def test_concurrent_add_atomicity(native_store_server):
    n_threads, n_incr = 8, 100

    def worker():
        c = StoreClient("127.0.0.1", native_store_server.port)
        for _ in range(n_incr):
            c.add("counter", 1)
        c.close()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    c = StoreClient("127.0.0.1", native_store_server.port)
    assert c.add("counter", 0) == n_threads * n_incr
    c.close()


def test_barriers_on_native(native_store_server):
    world = 4
    errors = []

    def member(i):
        try:
            c = StoreClient("127.0.0.1", native_store_server.port)
            barrier(c, "nb", world, timeout=10.0)
            reentrant_barrier(c, "nrb", i, world, timeout=10.0)
            if i == 0:
                reentrant_barrier(c, "nrb", i, world, timeout=10.0)
            c.close()
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=member, args=(i,)) for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors


def test_garbage_opcode_drops_conn_server_survives(nstore, native_store_server):
    import socket

    s = socket.create_connection(("127.0.0.1", native_store_server.port))
    s.sendall(b"\xff\x00\x00\x00\x00garbage")
    time.sleep(0.1)
    s.close()
    nstore.set("after", b"ok")
    assert nstore.get("after") == b"ok"


def test_native_faster_than_python_roundtrips(native_store_server, store_server):
    """Throughput sanity: the native server should beat asyncio on small-op
    roundtrips (not asserted strictly — just recorded + a sanity floor)."""

    def bench(port, n=2000):
        c = StoreClient("127.0.0.1", port)
        t0 = time.perf_counter()
        for i in range(n):
            c.add("bench", 1)
        dt = time.perf_counter() - t0
        c.close()
        return n / dt

    native_ops = bench(native_store_server.port)
    python_ops = bench(store_server.port)
    print(f"\nnative: {native_ops:,.0f} ops/s, asyncio: {python_ops:,.0f} ops/s, "
          f"speedup {native_ops / python_ops:.2f}x")
    assert native_ops > 2000  # sanity floor for a local roundtrip


# -- journal ----------------------------------------------------------------


def test_native_journal_restart_restores_state(tmp_path):
    from tpu_resiliency.store.native import NativeStoreServer

    journal = str(tmp_path / "store.journal")
    srv = NativeStoreServer(host="127.0.0.1", port=0, journal=journal).start()
    try:
        c = StoreClient("127.0.0.1", srv.port, timeout=10.0)
        c.set("rdzv/round", b"7")
        c.set("cycle/count", b"42")
        c.add("counter", 5)
        c.append("log", b"abc")
        c.append("log", b"def")
        c.set("doomed", b"x")
        c.delete("doomed")
        c.close()
        time.sleep(0.1)
    finally:
        srv.stop()

    srv2 = NativeStoreServer(host="127.0.0.1", port=0, journal=journal).start()
    try:
        assert srv2.replayed_keys == 4
        c = StoreClient("127.0.0.1", srv2.port, timeout=10.0)
        assert c.get("rdzv/round") == b"7"
        assert c.get("cycle/count") == b"42"
        assert c.get("counter") == b"5"
        assert c.get("log") == b"abcdef"
        assert c.try_get("doomed") is None
        c.close()
    finally:
        srv2.stop()


def test_native_journal_strip_prefix(tmp_path):
    from tpu_resiliency.store.native import NativeStoreServer

    journal = str(tmp_path / "store.journal")
    srv = NativeStoreServer(host="127.0.0.1", port=0, journal=journal).start()
    try:
        c = StoreClient("127.0.0.1", srv.port, timeout=10.0)
        c.set("shutdown", b"success")
        c.set("shutdown/ack/1", b"1")
        c.set("keepme", b"1")
        c.close()
        time.sleep(0.1)
    finally:
        srv.stop()
    srv2 = NativeStoreServer(
        host="127.0.0.1", port=0, journal=journal,
        journal_strip_prefixes=["shutdown"],
    ).start()
    try:
        c = StoreClient("127.0.0.1", srv2.port, timeout=10.0)
        assert c.try_get("shutdown") is None
        assert c.try_get("shutdown/ack/1") is None
        assert c.get("keepme") == b"1"
        c.close()
    finally:
        srv2.stop()


def test_native_journal_interop_with_python_server(tmp_path):
    """One journal format, two servers: state written under the asyncio
    server replays into the native server and vice versa."""
    from tpu_resiliency.store import StoreServer
    from tpu_resiliency.store.native import NativeStoreServer

    journal = str(tmp_path / "interop.journal")
    py = StoreServer(
        host="127.0.0.1", port=0, journal_path=journal
    ).start_in_thread()
    try:
        c = StoreClient("127.0.0.1", py.port, timeout=10.0)
        c.set("from-python", b"py-value")
        c.close()
    finally:
        py.stop()

    native = NativeStoreServer(
        host="127.0.0.1", port=0, journal=journal
    ).start()
    try:
        c = StoreClient("127.0.0.1", native.port, timeout=10.0)
        assert c.get("from-python") == b"py-value"
        c.set("from-native", b"cpp-value")
        c.close()
        time.sleep(0.1)
    finally:
        native.stop()

    py2 = StoreServer(
        host="127.0.0.1", port=0, journal_path=journal
    ).start_in_thread()
    try:
        c = StoreClient("127.0.0.1", py2.port, timeout=10.0)
        assert c.get("from-python") == b"py-value"
        assert c.get("from-native") == b"cpp-value"
        c.close()
    finally:
        py2.stop()


def test_native_journal_lock_rejects_second_instance(tmp_path):
    from tpu_resiliency.store.native import NativeStoreServer

    journal = str(tmp_path / "locked.journal")
    srv = NativeStoreServer(host="127.0.0.1", port=0, journal=journal).start()
    try:
        with pytest.raises(RuntimeError):
            NativeStoreServer(host="127.0.0.1", port=0, journal=journal).start()
    finally:
        srv.stop()


def test_native_journal_compaction_bounds_size(tmp_path):
    """Mutation churn past the cap compacts to a snapshot; state intact."""
    import os
    import subprocess as sp

    from tpu_resiliency.store.native import build_native_server

    journal = str(tmp_path / "churn.journal")
    binary = build_native_server()
    proc = sp.Popen(
        [binary, "--host", "127.0.0.1", "--port", "0",
         "--journal", journal, "--journal-max-bytes", "20000"],
        stderr=sp.PIPE, text=True,
    )
    try:
        line = proc.stderr.readline()
        import re as _re

        port = int(_re.search(r"listening on \S+:(\d+)", line).group(1))
        c = StoreClient("127.0.0.1", port, timeout=10.0)
        # ~100KB of churn on 10 keys -> must compact repeatedly
        for i in range(1000):
            c.set(f"churn/{i % 10}", (b"x" * 90) + str(i).encode())
        for i in range(10):
            expect = None
            for j in range(1000):
                if j % 10 == i:
                    expect = (b"x" * 90) + str(j).encode()
            assert c.get(f"churn/{i}") == expect
        c.close()
        time.sleep(0.2)
        size = os.path.getsize(journal)
        assert size < 40000, f"journal did not compact: {size} bytes"
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_native_control_plane_restart_keeps_cycle_numbering(tmp_path):
    """--journal --native-store: cycle numbering survives a control-plane
    restart under the C++ server (round-2 VERDICT weak #4)."""
    from tpu_resiliency.fault_tolerance.rendezvous import (
        K_CYCLE,
        RendezvousHost,
        k_done,
    )
    from tpu_resiliency.store.native import NativeStoreServer

    journal = str(tmp_path / "cp.journal")

    s1 = NativeStoreServer(host="127.0.0.1", port=0, journal=journal).start()
    c = StoreClient("127.0.0.1", s1.port)
    host = RendezvousHost(c, min_nodes=1)
    host.bootstrap()
    host.open_round()   # round 0, cycle 0
    assert int(c.get(K_CYCLE)) == 1
    c.set(k_done(0), b"1")
    c.close()
    time.sleep(0.1)
    s1.stop()

    s2 = NativeStoreServer(host="127.0.0.1", port=0, journal=journal).start()
    c2 = StoreClient("127.0.0.1", s2.port)
    host2 = RendezvousHost(c2, min_nodes=1)
    host2.bootstrap()  # no-op on restored state
    assert host2.current_round() == 0
    assert host2.open_round() == 1
    assert int(c2.get(K_CYCLE)) == 2  # numbering continued, no reset
    c2.close()
    time.sleep(0.1)
    s2.stop()
