"""End-to-end telemetry: instrumented components feeding the per-rank
exporter, exact log-drop accounting, and the utils satellite fixes
(profiling sink, deferred %r expansion)."""

import asyncio
import json
import logging
import os
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

from tpu_resiliency.telemetry import get_registry
from tpu_resiliency.telemetry.exporter import MetricsHTTPServer
from tests.test_telemetry import assert_valid_openmetrics


def _scrape(port):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ) as resp:
        return resp.read().decode()


class _MonitorServerThread:
    """RankMonitorServer's asyncio loop on a daemon thread (test_rank_monitor
    pattern)."""

    def __init__(self, cfg, socket_path):
        from tpu_resiliency.fault_tolerance.rank_monitor_server import (
            RankMonitorServer,
        )

        self.server = RankMonitorServer(cfg, socket_path)
        self._loop = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        assert self._started.wait(10)

    def _run(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(self.server.run_async(self._started))
        except Exception:  # noqa: BLE001
            pass

    def stop(self):
        if self._loop:
            self._loop.call_soon_threadsafe(
                lambda: [t.cancel() for t in asyncio.all_tasks(self._loop)]
            )
        self._thread.join(timeout=3)


def test_exporter_scrapes_all_series_during_restart_and_save(
    store, tmp_path
):
    """Acceptance: curl the per-rank exporter during a simulated in-process
    restart + async save; the exposition is valid OpenMetrics and carries
    heartbeat-latency, rendezvous-duration, restart-phase, checkpoint-drain,
    straggler, and log-drop series."""
    from tpu_resiliency.checkpointing import AsyncCheckpointer
    from tpu_resiliency.fault_tolerance.config import FaultToleranceConfig
    from tpu_resiliency.fault_tolerance.data import RankInfo
    from tpu_resiliency.fault_tolerance.rank_monitor_client import (
        RankMonitorClient,
    )
    from tpu_resiliency.fault_tolerance.rendezvous import (
        NodeDesc,
        RendezvousHost,
        RendezvousJoiner,
    )
    from tpu_resiliency.inprocess import Wrapper
    from tpu_resiliency.straggler.detector import Detector
    from tpu_resiliency.utils.log_funnel import LogForwarder

    exporter = MetricsHTTPServer(get_registry(), host="127.0.0.1").start()
    try:
        # -- heartbeat latency: real client -> real monitor over UDS
        cfg = FaultToleranceConfig(
            workload_check_interval=0.1, skip_section_response=False
        )
        mon = _MonitorServerThread(cfg, str(tmp_path / "monitor.sock"))
        client = RankMonitorClient(cfg)
        client.init_workload_monitoring(
            socket_path=str(tmp_path / "monitor.sock"),
            rank_info=RankInfo(global_rank=0, local_rank=0, pid=os.getpid()),
        )
        for _ in range(5):
            client.send_heartbeat()
        client.shutdown_workload_monitoring()
        mon.stop()

        # -- rendezvous round duration: host + one joiner over the store
        host = RendezvousHost(store, min_nodes=1, max_nodes=1, settle_time=0.05)
        host.bootstrap()
        host.open_round()
        result = {}

        def join():
            joiner = RendezvousJoiner(
                store.clone(), NodeDesc.create("n0", slots=1),
                open_poll_interval=0.05,
            )
            result["r"] = joiner.join(timeout=20.0)

        jt = threading.Thread(target=join)
        jt.start()
        host.close_round_when_ready(timeout=20.0)
        jt.join(timeout=20)
        assert result["r"].group_rank == 0

        # -- simulated in-process restart: fault at iteration 0, recover
        def train(call_wrapper=None):
            if call_wrapper.iteration == 0:
                raise ValueError("injected fault")
            return "recovered"

        wrapper = Wrapper(
            store_factory=lambda: store.clone(),
            group="telemetry-e2e",
            soft_timeout=3600.0,
            hard_timeout=7200.0,
            enable_monitor_process=False,
            enable_sibling_monitor=False,
            last_call_wait=0.0,
        )
        assert wrapper(train)() == "recovered"

        # -- async save with the drain-progress gauge polled mid-flight
        ckpt = AsyncCheckpointer()
        try:
            tree = {"w": np.ones((1 << 20,), np.float32)}
            ckpt.async_save(tree, str(tmp_path / "ckpt"), save_id="t")
            while ckpt.num_pending_saves:
                ckpt.drain_progress()
                ckpt.maybe_finalize()
                time.sleep(0.01)
            ckpt.drain_progress()
        finally:
            ckpt.close()

        # -- straggler verdicts (single-rank round)
        det = Detector(rank=0, world_size=1, report_interval=1, always_on=False)
        det.initialize()
        with det.detection_section("data"):
            time.sleep(0.001)
        report = det.generate_report()
        assert report.identify_stragglers() is not None

        # -- log-drop series: overflow a forwarder aimed at a dead port
        dead = socket.socket()
        dead.bind(("127.0.0.1", 0))
        dead_port = dead.getsockname()[1]
        dead.close()  # nothing listens here
        fwd = LogForwarder(
            "127.0.0.1", dead_port, source="t", batch_lines=10_000,
            batch_age=30.0, max_buffer=2,
        )
        rec = logging.LogRecord("t", logging.INFO, __file__, 1, "m", (), None)
        for _ in range(5):
            fwd.emit(rec)
        assert fwd.dropped_total == 3

        # -- the scrape itself
        body = _scrape(exporter.port)
    finally:
        exporter.close()

    assert_valid_openmetrics(body)
    for series in (
        "tpurx_heartbeat_send_latency_ns_count",
        "tpurx_heartbeat_received_total",
        "tpurx_rendezvous_round_duration_ns_count",
        "tpurx_rendezvous_join_latency_ns_count",
        'tpurx_restart_phase_latency_ns_bucket{phase="finalize"',
        "tpurx_restart_total_latency_ns_count",
        "tpurx_inprocess_restarts_total",
        "tpurx_ckpt_saves_total",
        "tpurx_ckpt_stage_bytes_total",
        "tpurx_ckpt_drain_progress",
        'tpurx_straggler_verdicts_total{verdict="nominal"}',
        'tpurx_straggler_score{rank="0"}',
        "tpurx_log_forwarder_dropped_total",
        "tpurx_store_ops_total",
        "tpurx_monitor_trips_total",
    ):
        assert series in body, f"series missing from exposition: {series}"
    # drop counter is cumulative across the process; this test added 3
    reg = get_registry()
    assert reg.value_of("tpurx_log_forwarder_dropped_total") >= 3


# ---- satellite: exact LogForwarder drop accounting --------------------------


def test_log_forwarder_exact_drop_accounting_end_to_end(tmp_path):
    """Force buffer overflow and assert the SAME drop count at all three
    observation points: the local ``dropped_total`` property, the registry
    counter, and the root funnel's consolidated file."""
    from tpu_resiliency.utils.log_funnel import LogForwarder, RootLogServer

    reg = get_registry()
    before = reg.value_of("tpurx_log_forwarder_dropped_total")
    root = RootLogServer(str(tmp_path / "consolidated.log"), host="127.0.0.1",
                         flush_age=0.05)
    fwd = LogForwarder(
        "127.0.0.1", root.port, source="rank7",
        batch_lines=10_000,  # kick never fires: flushes only by age
        batch_age=0.5,
        max_buffer=10,
    )
    try:
        rec = lambda i: logging.LogRecord(  # noqa: E731
            "t", logging.INFO, __file__, 1, f"line-{i}", (), None
        )
        # 17 emits in <<0.5s: 10 buffered, exactly 7 dropped
        for i in range(17):
            fwd.emit(rec(i))
        assert fwd.dropped_total == 7
        assert reg.value_of("tpurx_log_forwarder_dropped_total") - before == 7
        # the pending drop count rides the next batch to the root
        deadline = time.monotonic() + 10
        content = ""
        while time.monotonic() < deadline:
            fwd._kick.set()  # hasten the age-based flush
            with root._lock:
                root._file.flush()
            with open(tmp_path / "consolidated.log") as f:
                content = f.read()
            if "dropped 7 lines" in content:
                break
            time.sleep(0.05)
        assert "[logfunnel] rank7 dropped 7 lines" in content
        assert "[rank7] line-0" in content and "[rank7] line-9" in content
        assert "line-10" not in content  # the dropped ones never arrive
        # cumulative property keeps counting across episodes
        for i in range(3):
            fwd.emit(rec(100 + i))
        assert fwd.dropped_total == 7  # buffer drained: no new drops
    finally:
        fwd.close()
        root.close()


# ---- satellite: ProfilingRecorder sink + bounded history --------------------


class TestProfilingRecorder:
    def test_bounded_history_and_full_file(self, tmp_path):
        from tpu_resiliency.utils.profiling import (
            ProfilingEvent,
            ProfilingRecorder,
        )

        path = tmp_path / "prof.jsonl"
        rec = ProfilingRecorder(path=str(path), history=10)
        for i in range(50):
            rec.record(ProfilingEvent.FAILURE_DETECTED, i=i)
        assert len(rec.events) == 10  # bounded in memory
        assert rec.events[0]["i"] == 40  # oldest evicted
        rec.close()
        with open(path) as f:
            meta, *lines = [json.loads(l) for l in f]
        assert meta["event"] == "_flight_meta"  # alignment header
        assert len(lines) == 50  # file keeps the full stream
        assert [l["i"] for l in lines] == list(range(50))

    def test_persistent_fd_not_reopened_per_event(self, tmp_path):
        """Regression: the old implementation re-opened the sink per event —
        deleting the file mid-stream would silently recreate it.  With a
        held fd, writes keep flowing to the (unlinked) inode and no new
        file appears at the path."""
        from tpu_resiliency.utils.profiling import (
            ProfilingEvent,
            ProfilingRecorder,
        )

        path = tmp_path / "prof.jsonl"
        rec = ProfilingRecorder(path=str(path), history=100)
        rec.record(ProfilingEvent.FAILURE_DETECTED)
        assert path.exists()
        os.unlink(path)
        for _ in range(5):
            rec.record(ProfilingEvent.FAILURE_DETECTED)
        assert not path.exists(), "sink was re-opened per event"
        rec.close()

    def test_env_history_cap(self, tmp_path, monkeypatch):
        from tpu_resiliency.utils.profiling import (
            ProfilingEvent,
            ProfilingRecorder,
        )

        monkeypatch.setenv("TPURX_PROFILING_HISTORY", "3")
        rec = ProfilingRecorder()
        for i in range(9):
            rec.record(ProfilingEvent.FAILURE_DETECTED, i=i)
        assert [e["i"] for e in rec.events] == [6, 7, 8]

    def test_latency_ns_still_works_on_deque(self):
        from tpu_resiliency.utils.profiling import (
            ProfilingEvent,
            ProfilingRecorder,
        )

        rec = ProfilingRecorder(history=100)
        rec.record(ProfilingEvent.RENDEZVOUS_STARTED)
        rec.record(ProfilingEvent.RENDEZVOUS_COMPLETED)
        assert rec.latency_ns(
            ProfilingEvent.RENDEZVOUS_STARTED, ProfilingEvent.RENDEZVOUS_COMPLETED
        ) >= 0


# ---- satellite: deferred %r expansion in the file log sink ------------------


class TestLogFileRankExpansion:
    @pytest.fixture(autouse=True)
    def _restore_logger(self):
        yield
        # drop the test's file handler so later tests log to stderr only
        from tpu_resiliency.utils.logging import LogConfig, setup_logger

        setup_logger(LogConfig(), force=True)

    def test_rank_set_before_setup(self, tmp_path, monkeypatch):
        from tpu_resiliency.utils.logging import LogConfig, setup_logger

        monkeypatch.setenv("TPURX_RANK", "5")
        logger = setup_logger(
            LogConfig(to_file=str(tmp_path / "log_%r.txt")), force=True
        )
        logger.warning("hello")
        assert (tmp_path / "log_5.txt").exists()

    def test_rank_set_after_setup_before_first_record(self, tmp_path, monkeypatch):
        """The launcher order: import (setup) happens first, TPURX_RANK is
        exported later.  The old eager expansion baked in '?'."""
        from tpu_resiliency.utils.logging import LogConfig, setup_logger

        monkeypatch.delenv("TPURX_RANK", raising=False)
        monkeypatch.delenv("TPURX_GROUP_RANK", raising=False)
        monkeypatch.delenv("TPURX_INFRA_RANK", raising=False)
        logger = setup_logger(
            LogConfig(to_file=str(tmp_path / "log_%r.txt")), force=True
        )
        monkeypatch.setenv("TPURX_RANK", "7")  # after setup, before 1st record
        logger.warning("hello")
        assert (tmp_path / "log_7.txt").exists()
        assert not (tmp_path / "log_?.txt").exists()

    def test_rank_change_reopens_at_new_path(self, tmp_path, monkeypatch):
        from tpu_resiliency.utils.logging import LogConfig, setup_logger

        monkeypatch.setenv("TPURX_RANK", "1")
        logger = setup_logger(
            LogConfig(to_file=str(tmp_path / "log_%r.txt")), force=True
        )
        logger.warning("first")
        monkeypatch.setenv("TPURX_RANK", "2")  # re-rank across a restart cycle
        logger.warning("second")
        assert "first" in (tmp_path / "log_1.txt").read_text()
        assert "second" in (tmp_path / "log_2.txt").read_text()
