"""Property tests for the ns-scale quorum stamp scheme across the epoch wrap.

The v3 stamp contract (ISSUE 7 tentpole): host stamps are CLOCK_REALTIME
nanoseconds folded into ``[0, 2^63)``, age math is wrap-safe mod 2^63 with
the future==fresh clamp (a FUTURE stamp — NTP skew across processes, a
concurrently-stamping C thread — must read as fresh, never as an eras-stale
heartbeat tripping a spurious pod-wide restart), and the device lane
quantizes ages to saturating int32 units of ``2^15 ns``.  These tests sweep
the whole wrap with seeded random sampling (hypothesis is not in the image)
plus exhaustive boundary cases, and cross-check the C (ABI v3) and Python
stamp domains through the loaded ``.so``.
"""

import random

import numpy as np
import pytest

from tpu_resiliency.ops.quorum import (
    _AGE_CAP,
    _HALF_NS,
    _WRAP_NS,
    AGE_CAP_MS,
    DEV_QUANTUM_NS,
    DEV_SHIFT,
    QuorumMonitor,
    age_units,
    ages_ns_from_stamps,
    clamp_future_ns,
    load_beat_lib,
    make_quorum_fn,
    now_stamp_ns,
    pack_age_device,
    stamp_age_ns,
    units_to_ns,
    unpack_age_device,
)

RNG = random.Random(0xA6E5)

BOUNDARY_EPOCHS = [0, 1, _HALF_NS - 1, _HALF_NS, _HALF_NS + 1,
                   _WRAP_NS - 2, _WRAP_NS - 1]
BOUNDARY_AGES = [0, 1, DEV_QUANTUM_NS - 1, DEV_QUANTUM_NS,
                 999_999_999, units_to_ns(_AGE_CAP) - 1,
                 units_to_ns(_AGE_CAP), units_to_ns(_AGE_CAP) + 1,
                 _HALF_NS - 1]


def cases(n=2000):
    """Seeded (then, age) pairs spanning the full wrap, plus boundaries."""
    out = [(t, a) for t in BOUNDARY_EPOCHS for a in BOUNDARY_AGES]
    for _ in range(n):
        out.append((RNG.randrange(_WRAP_NS), RNG.randrange(_HALF_NS)))
    return out


def test_stamp_age_wraps_exactly():
    """age((then + age) mod W, then) == age for every age < W/2, including
    stamps that wrapped between beat and read."""
    for then, age in cases():
        now = (then + age) % _WRAP_NS
        assert stamp_age_ns(now, then) == age, (then, age)


def test_stamp_age_monotone_across_wrap():
    """Aging never decreases as time advances through the wrap point."""
    then = _WRAP_NS - 5
    ages = [stamp_age_ns((then + d) % _WRAP_NS, then) for d in range(0, 50)]
    assert ages == sorted(ages)
    assert ages[0] == 0 and ages[-1] == 49


def test_future_clamp_scalar_and_vector_agree():
    """The scalar clamp and the vector path fold IDENTICALLY: any age past
    the half-wrap horizon (i.e. a future stamp) reads as 0, everything
    below it reads exactly."""
    for then, age in cases(500):
        now = (then + age) % _WRAP_NS
        scalar = clamp_future_ns(stamp_age_ns(now, then))
        vec = int(ages_ns_from_stamps(now, np.asarray([then], dtype=np.int64))[0])
        assert scalar == vec == age, (then, age)
        # the symmetric pair: `then` is a FUTURE stamp seen from `now - age`
        past_now = (then - age) % _WRAP_NS if age else now
        scalar_f = clamp_future_ns(stamp_age_ns(past_now, then))
        vec_f = int(
            ages_ns_from_stamps(past_now, np.asarray([then], dtype=np.int64))[0]
        )
        assert scalar_f == vec_f
        if 0 < age < _HALF_NS:
            assert scalar_f == 0, (then, age)  # future == fresh


def test_age_units_quantize_and_saturate():
    """ns ages quantize to the 2^15 ns device quantum (floor) and saturate
    at int32 max instead of wrapping — the device only ever compares
    non-negative saturating units."""
    for _ in range(2000):
        age = RNG.randrange(_HALF_NS)
        u = int(age_units(np.asarray([age], dtype=np.uint64))[0])
        assert u == min(age >> DEV_SHIFT, 2 ** 31 - 1), age
    assert int(age_units(np.asarray([_HALF_NS - 1], dtype=np.uint64))[0]) \
        == 2 ** 31 - 1


def test_pack_unpack_roundtrip_and_cap():
    for _ in range(2000):
        units = RNG.randrange(0, 1 << 20)      # past the cap on purpose
        dev = RNG.randrange(0, 1 << 16)
        packed = pack_age_device(
            np.asarray([units], dtype=np.int64), np.asarray([dev])
        )[0]
        got_units, got_dev = unpack_age_device(int(packed))
        assert got_dev == dev
        assert got_units == min(units, _AGE_CAP)
        # packed stays a valid non-negative int32 (pmax-safe)
        assert 0 <= packed <= 2 ** 31 - 1


def test_pack_orders_lexicographically_by_age_then_device():
    """One pmax over packed values must pick the max (age, device) — the
    property the single-collective identify mode rests on."""
    for _ in range(2000):
        a1, a2 = RNG.randrange(_AGE_CAP + 100), RNG.randrange(_AGE_CAP + 100)
        d1, d2 = RNG.randrange(1 << 16), RNG.randrange(1 << 16)
        p1 = int(pack_age_device(np.asarray([a1]), np.asarray([d1]))[0])
        p2 = int(pack_age_device(np.asarray([a2]), np.asarray([d2]))[0])
        key1 = (min(a1, _AGE_CAP), d1)
        key2 = (min(a2, _AGE_CAP), d2)
        assert (p1 > p2) == (key1 > key2) or key1 == key2


def test_saturated_ages_still_compare_correctly():
    """Ages at/past the 15-bit cap saturate but never sort BELOW a smaller
    age (the cap loses magnitude, not ordering) — and the cap itself sits
    above every shipped default budget."""
    small = int(pack_age_device(np.asarray([100]), np.asarray([7]))[0])
    capped = int(pack_age_device(np.asarray([_AGE_CAP]), np.asarray([3]))[0])
    way_past = int(pack_age_device(np.asarray([10 * _AGE_CAP]), np.asarray([3]))[0])
    assert capped == way_past            # saturation
    assert way_past > small              # ordering survives
    assert AGE_CAP_MS > 1000.0           # default budgets (<=1s) can trip


def test_current_stamp_clamps_future_stamps_across_wrap():
    """A native-beater stamp in the FUTURE (concurrent C thread, NTP skew)
    must win over a stale manual beat — not read as a half-wrap-stale
    heartbeat.  Stamps are built relative to the REAL clock (the method
    re-reads it); the modulo fold exercises the wrap whenever the shifted
    stamp crosses the boundary, and the symmetric case (stale native,
    fresh manual) guards the other arm."""
    import ctypes

    mon = QuorumMonitor.__new__(QuorumMonitor)  # no mesh/jit needed
    deltas_ns = [10_000, 5_000_000, 100_000_000, 2_000_000_000]
    deltas_ns += [RNG.randrange(1, 3_000_000_000) for _ in range(200)]
    for delta in deltas_ns:
        now = now_stamp_ns()
        future = (now + delta) % _WRAP_NS
        stale = (now - 10_000_000_000) % _WRAP_NS
        mon._last_beat_ns = stale
        mon._native_slot = ctypes.c_int64(future)
        assert mon._current_stamp() == future, (delta,)
        # symmetric: a stale native slot must not shadow a fresh manual beat
        fresh = now_stamp_ns()
        mon._last_beat_ns = fresh
        mon._native_slot = ctypes.c_int64(stale)
        assert mon._current_stamp() == fresh, (delta,)


@pytest.fixture(scope="module")
def one_dev_mesh():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:1]), ("d",))


def test_quorum_fn_future_stamp_reads_fresh(one_dev_mesh):
    """End-to-end through the real collective: a stamp ahead of the host
    clock yields age ~0, not a saturated/huge age (the wrap-bug class this
    file pins down — pre-clamp it returned a half-wrap age, a guaranteed
    false trip; in identify mode it saturated the 15-bit cap, same trip)."""
    fn = make_quorum_fn(one_dev_mesh, use_pallas=False)
    future = (now_stamp_ns() + 4_000_000_000) % _WRAP_NS
    age_ns = fn(np.asarray([future], dtype=np.int64))
    assert 0 <= age_ns < 1_000_000_000, age_ns

    fn_id = make_quorum_fn(one_dev_mesh, use_pallas=False, identify=True)
    age_id, dev = fn_id(np.asarray([future], dtype=np.int64))
    assert 0 <= age_id < 1_000_000_000, age_id
    assert dev == 0


def test_quorum_fn_stale_stamp_across_wrap_reads_stale(one_dev_mesh):
    """A stamp that beat BEFORE the wrap point while `now` sits after it
    must still read as its true age (a raw pmin/pmax over wrapped stamps
    would mask it until the next wrap)."""
    fn = make_quorum_fn(one_dev_mesh, use_pallas=False)
    stale = (now_stamp_ns() - 7_000_000_000) % _WRAP_NS  # 7s, possibly wrapped
    age_ns = fn(np.asarray([stale], dtype=np.int64))
    assert 6_500_000_000 <= age_ns <= 60_000_000_000, age_ns


def test_quorum_fn_age_resolution_is_device_quantum(one_dev_mesh):
    """The collective's answer is ns quantized to 2^15 ns — a 10 ms-stale
    stamp must read within one quantum of truth (the old path's 1 ms stamp
    unit was the named detection floor; the quantum is 30x finer)."""
    fn = make_quorum_fn(one_dev_mesh, use_pallas=False)
    stale = (now_stamp_ns() - 10_000_000) % _WRAP_NS   # 10 ms
    age_ns = fn(np.asarray([stale], dtype=np.int64))
    assert age_ns % DEV_QUANTUM_NS == 0
    assert 10_000_000 - DEV_QUANTUM_NS <= age_ns <= 13_000_000, age_ns


# -- C (ABI v3) / Python stamp parity through the loaded .so ----------------

@pytest.fixture(scope="module")
def beat_lib():
    lib = load_beat_lib()
    if lib is None:
        pytest.skip("native beat helper unavailable (no toolchain)")
    return lib


def test_c_python_epoch_parity(beat_lib):
    """The C stamp domain IS the Python stamp domain: same clock, same
    fold width — asserted through the loaded .so, not a source comment."""
    assert int(beat_lib.tpurx_beat_abi_v3()) == 3
    assert int(beat_lib.tpurx_beat_wrap_bits()) == 63
    c_now = int(beat_lib.tpurx_beat_now_ns())
    py_now = now_stamp_ns()
    # same epoch: the two reads happened within this test, so the wrap-safe
    # age between them is sub-second in EITHER direction
    delta = min(stamp_age_ns(py_now, c_now), stamp_age_ns(c_now, py_now))
    assert delta < 1_000_000_000, (c_now, py_now)


def test_c_stamp_feeds_python_age_math(beat_lib):
    """A live native beater's slot stamp, read from Python, ages correctly
    through the shared helpers (the exact mixed-source path
    ``QuorumMonitor._current_stamp`` runs)."""
    import time as _time

    from tpu_resiliency.ops.quorum import NativeBeater

    b = NativeBeater(interval_s=0.0005)
    if not b.start():
        pytest.skip("beater failed to start")
    try:
        _time.sleep(0.05)
        age = clamp_future_ns(stamp_age_ns(now_stamp_ns(), b.stamp_ns))
        # fresh: within a few beat intervals even on a loaded host
        assert age < 500_000_000, age
    finally:
        b.stop()
    frozen = b.stamp_ns
    _time.sleep(0.02)
    age = clamp_future_ns(stamp_age_ns(now_stamp_ns(), frozen))
    assert age >= 15_000_000, age  # frozen stamp ages in the ns domain
