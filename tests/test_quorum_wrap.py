"""Property tests for the quorum stamp scheme across the 24.8-day int32 wrap.

VERDICT r5 weak #6: ``stamp_age_ms``'s wrap behavior and the identify-mode
15-bit age cap were asserted only at small offsets.  These tests sweep the
whole wrap with seeded random sampling (hypothesis is not in the image) plus
exhaustive boundary cases, and pin the fix for the wrap bug the sweep found:
a FUTURE stamp (NTP skew across processes, a concurrent native beater) used
to fold to a ~2^31 ms age inside ``make_quorum_fn`` — one such tick read as
a 24.8-day-stale heartbeat and tripped a spurious pod-wide restart.
"""

import random

import numpy as np
import pytest

from tpu_resiliency.ops.quorum import (
    _AGE_CAP,
    _WRAP,
    QuorumMonitor,
    make_quorum_fn,
    now_stamp_ms,
    pack_age_device,
    stamp_age_ms,
    unpack_age_device,
)

RNG = random.Random(0xA6E5)

BOUNDARY_EPOCHS = [0, 1, _WRAP // 2 - 1, _WRAP // 2, _WRAP // 2 + 1,
                   _WRAP - 2, _WRAP - 1]
BOUNDARY_AGES = [0, 1, 999, _AGE_CAP - 1, _AGE_CAP, _AGE_CAP + 1,
                 _WRAP // 2 - 1]


def cases(n=2000):
    """Seeded (then, age) pairs spanning the full wrap, plus boundaries."""
    out = [(t, a) for t in BOUNDARY_EPOCHS for a in BOUNDARY_AGES]
    for _ in range(n):
        out.append((RNG.randrange(_WRAP), RNG.randrange(_WRAP // 2)))
    return out


def test_stamp_age_wraps_exactly():
    """age((then + age) mod W, then) == age for every age < W/2, including
    stamps that wrapped between beat and read."""
    for then, age in cases():
        now = (then + age) % _WRAP
        assert stamp_age_ms(now, then) == age, (then, age)


def test_stamp_age_monotone_across_wrap():
    """Aging never decreases as time advances through the wrap point."""
    then = _WRAP - 5
    ages = [stamp_age_ms((then + d) % _WRAP, then) for d in range(0, 50)]
    assert ages == sorted(ages)
    assert ages[0] == 0 and ages[-1] == 49


def test_pack_unpack_roundtrip_and_cap():
    for _ in range(2000):
        age = RNG.randrange(0, 1 << 20)       # past the cap on purpose
        dev = RNG.randrange(0, 1 << 16)
        packed = pack_age_device(
            np.asarray([age], dtype=np.int64), np.asarray([dev])
        )[0]
        got_age, got_dev = unpack_age_device(int(packed))
        assert got_dev == dev
        assert got_age == min(age, _AGE_CAP)
        # packed stays a valid non-negative int32 (pmax-safe)
        assert 0 <= packed <= 2**31 - 1


def test_pack_orders_lexicographically_by_age_then_device():
    """One pmax over packed values must pick the max (age, device) — the
    property the single-collective identify mode rests on."""
    for _ in range(2000):
        a1, a2 = RNG.randrange(_AGE_CAP + 100), RNG.randrange(_AGE_CAP + 100)
        d1, d2 = RNG.randrange(1 << 16), RNG.randrange(1 << 16)
        p1 = int(pack_age_device(np.asarray([a1]), np.asarray([d1]))[0])
        p2 = int(pack_age_device(np.asarray([a2]), np.asarray([d2]))[0])
        key1 = (min(a1, _AGE_CAP), d1)
        key2 = (min(a2, _AGE_CAP), d2)
        assert (p1 > p2) == (key1 > key2) or key1 == key2


def test_saturated_ages_still_compare_correctly():
    """Ages at/past the 15-bit cap saturate but never sort BELOW a smaller
    age (the cap loses magnitude, not ordering)."""
    small = int(pack_age_device(np.asarray([100]), np.asarray([7]))[0])
    capped = int(pack_age_device(np.asarray([_AGE_CAP]), np.asarray([3]))[0])
    way_past = int(pack_age_device(np.asarray([10 * _AGE_CAP]), np.asarray([3]))[0])
    assert capped == way_past            # saturation
    assert way_past > small              # ordering survives


def test_current_stamp_clamps_future_stamps_across_wrap():
    """A native-beater stamp a few ms in the FUTURE (concurrent C thread,
    NTP skew) must win over a stale manual beat — not read as ~2^31 ms
    stale.  Stamps are built relative to the REAL clock (the method
    re-reads it); the modulo fold exercises the wrap whenever the shifted
    stamp crosses the boundary, and the symmetric case (stale native,
    fresh manual) guards the other arm."""
    import ctypes

    mon = QuorumMonitor.__new__(QuorumMonitor)  # no mesh/jit needed
    for delta in [1, 5, 100, 2000] + [RNG.randrange(1, 3000) for _ in range(200)]:
        now = now_stamp_ms()
        future = (now + delta) % _WRAP
        stale = (now - 10_000) % _WRAP
        mon._last_beat_ms = stale
        mon._native_slot = ctypes.c_int64(future)
        assert mon._current_stamp() == future, (delta,)
        # symmetric: a stale native slot must not shadow a fresh manual beat
        fresh = now_stamp_ms()
        mon._last_beat_ms = fresh
        mon._native_slot = ctypes.c_int64(stale)
        assert mon._current_stamp() == fresh, (delta,)


@pytest.fixture(scope="module")
def one_dev_mesh():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:1]), ("d",))


def test_quorum_fn_future_stamp_reads_fresh(one_dev_mesh):
    """End-to-end through the real collective: a stamp ahead of the host
    clock yields age ~0, not a saturated/huge age (the wrap bug this file
    pinned down — it previously returned ~2^31 ms, a guaranteed false
    trip; in identify mode it saturated the 15-bit cap, same trip)."""
    fn = make_quorum_fn(one_dev_mesh, use_pallas=False)
    future = (now_stamp_ms() + 4000) % _WRAP
    age = fn(np.asarray([future], dtype=np.int64))
    assert 0 <= age < 1000, age

    fn_id = make_quorum_fn(one_dev_mesh, use_pallas=False, identify=True)
    age_id, dev = fn_id(np.asarray([future], dtype=np.int64))
    assert 0 <= age_id < 1000, age_id
    assert dev == 0


def test_quorum_fn_stale_stamp_across_wrap_reads_stale(one_dev_mesh):
    """A stamp that beat BEFORE the wrap point while `now` sits after it
    must still read as its true age (a raw pmin/pmax over wrapped stamps
    would mask it for ~24.8 days)."""
    fn = make_quorum_fn(one_dev_mesh, use_pallas=False)
    stale = (now_stamp_ms() - 7000) % _WRAP   # 7s stale, possibly wrapped
    age = fn(np.asarray([stale], dtype=np.int64))
    assert 6500 <= age <= 60_000, age
