"""Sharded control-plane store: routing, failover, journal crash
consistency, and the shard-kill-mid-round soak smoke."""

import json
import signal
import threading
import time

import pytest

from tpu_resiliency.store import (
    PrefixStore,
    ShardMap,
    ShardServerGroup,
    ShardedStoreClient,
    StoreClient,
    StoreServer,
    barrier,
    reentrant_barrier,
    spawn_shard_subprocess,
    tree_gather,
)
from tpu_resiliency.store.barrier import BarrierTimeout
from tpu_resiliency.store.client import StoreError, StoreTimeout
from tpu_resiliency.store.sharding import free_port
from tpu_resiliency.store.tree import combine_json_merge
from tpu_resiliency.telemetry import get_registry


def _counter(name, site):
    return get_registry().value_of(name, {"site": site}) or 0.0


@pytest.fixture
def shard_group(tmp_path):
    group = ShardServerGroup(
        4, journal_base=str(tmp_path / "journal")
    ).start()
    yield group
    group.stop()


# -- shard map ----------------------------------------------------------------


class TestShardMap:
    def test_stable_and_total(self):
        m = ShardMap([("h1", 1), ("h2", 2), ("h3", 3)])
        for key in (b"a", b"rdzv/active_round", b"barrier/x/count", b"z" * 100):
            idx = m.shard_for(key)
            assert 0 <= idx < 3
            assert m.shard_for(key) == idx  # deterministic

    def test_distribution_reasonably_balanced(self):
        m = ShardMap([("h", p) for p in range(1, 5)])
        counts = [0] * 4
        for i in range(4000):
            counts[m.shard_for(f"key/{i}".encode())] += 1
        assert min(counts) > 4000 / 4 * 0.5, counts  # no starved shard

    def test_single_shard_short_circuits(self):
        m = ShardMap([("h", 1)])
        assert all(m.shard_for(f"k{i}".encode()) == 0 for i in range(50))

    def test_json_roundtrip(self):
        m = ShardMap([("127.0.0.1", 1234), ("127.0.0.1", 1235)], vnodes=32)
        m2 = ShardMap.from_json(m.to_json())
        assert m2.endpoints == m.endpoints
        for i in range(100):
            k = f"key/{i}".encode()
            assert m.shard_for(k) == m2.shard_for(k)

    def test_remap_moves_fraction_not_all(self):
        eps = [("h", p) for p in range(1, 5)]
        m4 = ShardMap(eps)
        m5 = ShardMap(eps + [("h", 5)])
        keys = [f"key/{i}".encode() for i in range(2000)]
        moved = sum(
            1
            for k in keys
            if m4.endpoints[m4.shard_for(k)] != m5.endpoints[m5.shard_for(k)]
        )
        # consistent hashing: ~1/5 of keys move, never the bulk
        assert moved < len(keys) * 0.45, moved


# -- sharded client over a live shard fleet ----------------------------------


class TestShardedClient:
    def test_primitive_surface(self, shard_group):
        c = shard_group.client(timeout=10.0)
        c.set("a", b"1")
        assert c.get("a") == b"1"
        assert c.try_get("missing") is None
        assert c.add("ctr", 5) == 5
        assert c.add("ctr", 2) == 7
        assert c.append("log", b"xy") == 2
        ok, v = c.compare_set_ex("cas", b"", b"first")
        assert ok and v == b"first"
        ok, v = c.compare_set_ex("cas", b"nope", b"second")
        assert not ok and v == b"first"
        assert c.delete("a") is True
        assert c.delete("a") is False
        assert c.ping() is True
        c.close()

    def test_keys_actually_spread_over_shards(self, shard_group):
        c = shard_group.client()
        c.multi_set({f"spread/{i}": b"v" for i in range(256)})
        per_shard = []
        for srv in shard_group.servers:
            direct = StoreClient("127.0.0.1", srv.port)
            per_shard.append(len(direct.list_keys("spread/")))
            direct.close()
        assert sum(per_shard) == 256
        assert all(n > 0 for n in per_shard), per_shard
        # num_keys / list_keys recombine the fleet view
        assert len(c.list_keys("spread/")) == 256
        c.close()

    def test_multi_get_per_key_none_across_shards(self, shard_group):
        c = shard_group.client()
        c.multi_set({f"m/{i}": str(i).encode() for i in range(16)})
        keys = [f"m/{i}" for i in range(16)] + ["m/nope", "m/gone"]
        out = c.multi_get(keys)
        assert out[:16] == [str(i).encode() for i in range(16)]
        assert out[16:] == [None, None]
        c.close()

    def test_wait_and_check_across_shards(self, shard_group):
        c = shard_group.client(timeout=10.0)
        keys = [f"w/{i}" for i in range(8)]  # hash over several shards
        c.multi_set({k: b"1" for k in keys[:-1]})
        assert c.check(keys[:-1]) is True
        assert c.check(keys) is False

        def setter():
            time.sleep(0.2)
            c2 = shard_group.client()
            c2.set(keys[-1], b"1")
            c2.close()

        t = threading.Thread(target=setter)
        t.start()
        c.wait(keys, timeout=10.0)
        t.join()
        with pytest.raises(StoreTimeout):
            c.wait(["never/there"], timeout=0.3)
        c.close()

    def test_prefix_store_and_barriers_over_shards(self, shard_group):
        ps = PrefixStore("iter/7", shard_group.client(timeout=10.0))
        ps.set("k", b"v")
        assert ps.get("k") == b"v"
        world = 4
        errors = []

        def member(i):
            c = shard_group.client(timeout=10.0)
            try:
                barrier(c, "sb", world, timeout=10.0)
                reentrant_barrier(c, "srb", i, world, timeout=10.0)
                if i == 0:  # re-entry must not deadlock or overflow
                    reentrant_barrier(c, "srb", i, world, timeout=10.0)
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)
            finally:
                c.close()

        threads = [threading.Thread(target=member, args=(i,)) for i in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        ps.close()

    def test_bootstrap_from_seed(self, shard_group):
        host, port = shard_group.servers[0].host, shard_group.servers[0].port
        c = ShardedStoreClient.from_bootstrap(host, port, timeout=10.0)
        assert len(c.endpoints) == 4
        c.set("boot", b"strapped")
        assert c.get("boot") == b"strapped"
        c.close()

    def test_tree_gather_over_sharded_store(self, shard_group):
        world, results, errors = 12, {}, []

        def run(rank):
            c = shard_group.client(timeout=15.0)
            try:
                results[rank] = tree_gather(
                    c, rank, world, prefix="sh/t0",
                    payload=json.dumps({rank: rank * 2}).encode(),
                    combine=combine_json_merge, timeout=15.0, fanout=3,
                    broadcast=True, site="test",
                )
            except Exception as exc:  # noqa: BLE001
                errors.append((rank, exc))
            finally:
                c.close()

        threads = [threading.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors[:3]
        expected = {str(r): r * 2 for r in range(world)}
        assert all(json.loads(results[r]) == expected for r in range(world))


# -- reentrant barrier: O(1) arrival log ------------------------------------


class TestReentrantBarrierLog:
    def test_timeout_names_missing_ranks(self, store):
        with pytest.raises(BarrierTimeout) as ei:
            reentrant_barrier(store, "naming", 2, 5, timeout=0.5)
        assert ei.value.arrived == 1
        assert ei.value.world_size == 5
        assert ei.value.missing == [0, 1, 3, 4]

    def test_one_arrival_key_regardless_of_world(self, store):
        world = 16
        errors = []

        def member(i, server_port):
            c = StoreClient("127.0.0.1", server_port)
            try:
                reentrant_barrier(c, "o1", i, world, timeout=15.0)
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)
            finally:
                c.close()

        port = store.port
        threads = [
            threading.Thread(target=member, args=(i, port)) for i in range(world)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        # O(1) keys: one arrival log + one done key — not one key per rank
        assert sorted(store.list_keys("barrier/o1/")) == [
            b"barrier/o1/arrivals", b"barrier/o1/done",
        ]

    def test_survivor_completes_after_arriver_crash(self, store):
        """A rank dying between its APPEND and the done-set must not wedge
        the barrier: any waiter completes it from the log on its next poll."""
        # simulate the crashed completer: its arrival is in the log, but
        # done was never set
        store.append("barrier/cw/arrivals", "1,")
        t0 = time.monotonic()
        reentrant_barrier(store, "cw", 0, 2, timeout=10.0)
        assert time.monotonic() - t0 < 5.0

    def test_ranks_subset(self, store):
        reentrant_barrier(store, "sub", 3, 8, timeout=5.0, ranks=[3])
        with pytest.raises(BarrierTimeout) as ei:
            reentrant_barrier(store, "sub2", 3, 8, timeout=0.4, ranks=[3, 5])
        assert ei.value.missing == [5]


# -- failover: shard death mid-op --------------------------------------------


class TestShardFailover:
    def test_mid_wait_shard_sigkill_with_replacement(self, tmp_path):
        """A WAIT parked on a shard survives SIGKILL + journal-replayed
        replacement on the same endpoint: the caller sees one (slow) round
        trip, and the reconnect retries land on the store_connect site."""
        ports = [free_port(), free_port()]
        journals = [str(tmp_path / f"j{i}") for i in range(2)]
        procs = [
            spawn_shard_subprocess(p, journal=j)
            for p, j in zip(ports, journals)
        ]
        endpoints = [f"127.0.0.1:{p}" for p in ports]
        try:
            c = ShardedStoreClient(endpoints, timeout=60.0)
            victim = c.map.shard_for(b"late/key")
            released = {}

            def block():
                try:
                    c.wait(["late/key"], timeout=45.0)
                    released["ok"] = True
                except Exception as exc:  # noqa: BLE001
                    released["err"] = exc

            t = threading.Thread(target=block)
            t.start()
            time.sleep(0.5)  # parked server-side
            backoffs_before = _counter(
                "tpurx_retry_backoffs_total", "store_connect"
            )
            procs[victim].send_signal(signal.SIGKILL)
            procs[victim].wait(timeout=10)
            time.sleep(1.0)  # dead window: the waiter must back off into it
            procs[victim] = spawn_shard_subprocess(
                ports[victim], journal=journals[victim]
            )
            setter = ShardedStoreClient(endpoints, timeout=20.0)
            setter.set("late/key", b"v")
            t.join(timeout=30)
            assert released.get("ok"), released
            assert c.get("late/key", timeout=5.0) == b"v"
            assert (
                _counter("tpurx_retry_backoffs_total", "store_connect")
                > backoffs_before
            )
            setter.close()
            c.close()
        finally:
            for p in procs:
                p.kill()

    def test_mid_cas_shard_sigkill_with_replacement(self, tmp_path):
        """COMPARE_SET issued into a dead shard succeeds once the journal-
        replayed replacement is up — one retried round trip, not an error."""
        port = free_port()
        journal = str(tmp_path / "jcas")
        proc = spawn_shard_subprocess(port, journal=journal)
        try:
            c = ShardedStoreClient([f"127.0.0.1:{port}"], timeout=30.0)
            c.set("warm", b"1")  # established socket to the doomed shard
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
            proc = spawn_shard_subprocess(port, journal=journal)
            ok, v = c.compare_set_ex("cas/k", b"", b"claimed")
            assert ok and v == b"claimed"
            assert c.get("warm", timeout=5.0) == b"1"  # journal replayed
            c.close()
        finally:
            proc.kill()

    def test_cas_recovery_branches_and_site_label(self, shard_group):
        """Deterministic recovery semantics: a 'connection lost after send'
        is retried under the store_cas_failover site; when the replacement
        already holds ``desired`` the first send is recognized as applied."""
        c = shard_group.client(timeout=10.0)
        idx = c._shard_idx("det/k")

        def arm_one_failure():
            inner = c._clients[idx]
            orig = inner.compare_set_ex
            state = {"fired": False}

            def flaky(key, expected, desired):
                if not state["fired"]:
                    state["fired"] = True
                    raise StoreError(
                        "store op COMPARE_SET connection lost after send; "
                        "not retrying non-idempotent op: injected"
                    )
                return orig(key, expected, desired)

            inner.compare_set_ex = flaky

        attempts_before = _counter(
            "tpurx_retry_attempts_total", "store_cas_failover"
        )
        arm_one_failure()
        ok, v = c.compare_set_ex("det/k", b"", b"v1")
        assert ok and v == b"v1"
        assert (
            _counter("tpurx_retry_attempts_total", "store_cas_failover")
            > attempts_before
        )
        # applied-before-death branch: the key already holds `desired` when
        # the client re-inspects — recognized as OUR swap, no re-issue
        # (a blind re-issue with expected=b"" would CAS_FAIL)
        c.set("det/k2", b"v2")
        arm_one_failure_key2 = c._shard_idx("det/k2")
        inner2 = c._clients[arm_one_failure_key2]
        orig2 = inner2.compare_set_ex
        state2 = {"fired": False}

        def flaky2(key, expected, desired):
            if not state2["fired"]:
                state2["fired"] = True
                raise StoreError(
                    "store op COMPARE_SET connection lost after send: injected"
                )
            return orig2(key, expected, desired)

        inner2.compare_set_ex = flaky2
        ok2, v2 = c.compare_set_ex("det/k2", b"", b"v2")
        assert ok2 and v2 == b"v2"
        c.close()


# -- journal compaction crash consistency ------------------------------------


class TestCompactionCrashConsistency:
    def test_kill_mid_write_snapshot_loses_nothing(self, tmp_path):
        """The satellite: die mid-``write_snapshot`` (fault hook: os._exit
        after N snapshot records), restart from the journal, and every ACKED
        mutation — including ones acked WHILE the snapshot was being
        written — replays with no loss and no duplication."""
        port = free_port()
        journal = str(tmp_path / "crash.journal")
        proc = spawn_shard_subprocess(
            port,
            journal=journal,
            journal_max_bytes=2048,  # compaction after ~25 writes
            env={"TPURX_STORE_TEST_COMPACT_CRASH": "2"},
        )
        client = StoreClient("127.0.0.1", port, timeout=5.0, retries=0)
        acked = {}
        try:
            for i in range(500):
                key = f"k{i}"
                val = f"v{i}".encode().ljust(64, b"x")
                client.set(key, val)
                acked[key] = val
        except (StoreError, StoreTimeout):
            pass  # the injected crash severed the connection
        client.close()
        proc.wait(timeout=30)
        assert proc.returncode == 137  # died inside write_snapshot
        assert len(acked) > 20, "crash fired before compaction?"

        srv = StoreServer(
            host="127.0.0.1", port=0, journal_path=journal
        ).start_in_thread()
        try:
            c2 = StoreClient("127.0.0.1", srv.port, timeout=10.0)
            for key, val in acked.items():
                assert c2.get(key, timeout=5.0) == val, f"lost acked {key}"
            # no duplicated/fabricated records: the replayed keyspace is the
            # acked set, plus at most the single in-flight unacked write
            n = c2.num_keys()
            assert len(acked) <= n <= len(acked) + 1, (len(acked), n)
            c2.close()
        finally:
            srv.stop()


# -- soak smoke: shard kill mid-rendezvous + verdict round --------------------


class TestShardKillMidRound:
    def test_rendezvous_and_verdict_survive_shard_kill(self, tmp_path):
        """The acceptance gate: SIGKILL one shard during an active
        rendezvous round, bring up its journal-replayed replacement, and the
        round closes with every node assigned; then a verdict-style tree
        round whose leaf payloads predate a second kill completes from the
        replayed journal.  No caller sees an error — the pod-wide-restart
        path is never entered."""
        from tpu_resiliency.fault_tolerance.rendezvous import (
            NodeDesc,
            RendezvousHost,
            RendezvousJoiner,
            k_join_count,
        )

        ports = [free_port(), free_port()]
        journals = [str(tmp_path / f"soak{i}") for i in range(2)]
        procs = [
            spawn_shard_subprocess(p, journal=j)
            for p, j in zip(ports, journals)
        ]
        endpoints = [f"127.0.0.1:{p}" for p in ports]
        n_nodes = 4
        try:
            host_client = ShardedStoreClient(endpoints, timeout=90.0)
            host = RendezvousHost(
                host_client, min_nodes=n_nodes, max_nodes=n_nodes,
                settle_time=0.2,
            )
            host.bootstrap()
            round_num = host.open_round()
            results, errors = {}, []

            def joiner(i):
                c = ShardedStoreClient(endpoints, timeout=90.0)
                try:
                    results[i] = RendezvousJoiner(
                        c, NodeDesc.create(node_id=f"soak-{i}", slots=1),
                        open_poll_interval=0.05,
                    ).join(timeout=60.0)
                except Exception as exc:  # noqa: BLE001
                    errors.append((i, exc))
                finally:
                    c.close()

            closer = threading.Thread(
                target=lambda: host.close_round_when_ready(timeout=60.0)
            )
            closer.start()
            early = [
                threading.Thread(target=joiner, args=(i,)) for i in range(3)
            ]
            for t in early:
                t.start()
            probe = ShardedStoreClient(endpoints, timeout=30.0)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if (probe.try_get(k_join_count(round_num)) or b"0") == b"3":
                    break
                time.sleep(0.05)
            # kill one shard MID-ROUND (3 joiners parked, round open)
            procs[1].send_signal(signal.SIGKILL)
            procs[1].wait(timeout=10)
            time.sleep(0.5)
            procs[1] = spawn_shard_subprocess(ports[1], journal=journals[1])
            late = threading.Thread(target=joiner, args=(3,))
            late.start()
            closer.join(timeout=60)
            for t in early:
                t.join(timeout=60)
            late.join(timeout=60)
            assert not errors, errors
            assert len(results) == n_nodes
            assert all(
                r.role.value == "participant" and r.group_world_size == n_nodes
                for r in results.values()
            )

            # verdict-style tree round across a second kill: leaves publish,
            # the shard dies and is journal-replayed, then the root gathers
            for rank in (1, 2, 3):
                tree_gather(
                    probe, rank, 4, prefix="soak/verdict/0",
                    payload=json.dumps({rank: {"bad_holder": None}}).encode(),
                    combine=combine_json_merge, timeout=20.0, fanout=4,
                    site="test",
                )
            procs[1].send_signal(signal.SIGKILL)
            procs[1].wait(timeout=10)
            procs[1] = spawn_shard_subprocess(ports[1], journal=journals[1])
            merged = tree_gather(
                probe, 0, 4, prefix="soak/verdict/0",
                payload=json.dumps({0: {"bad_holder": 2}}).encode(),
                combine=combine_json_merge, timeout=30.0, fanout=4,
                site="test",
            )
            verdicts = {int(r): v for r, v in json.loads(merged).items()}
            assert set(verdicts) == {0, 1, 2, 3}
            assert verdicts[0]["bad_holder"] == 2
            probe.close()
            host_client.close()
        finally:
            for p in procs:
                p.kill()


# -- epoch + spares: map round-trip and promotion routing ---------------------


class TestShardMapEpoch:
    def test_epoch_and_spares_json_roundtrip(self):
        m = ShardMap(
            [("127.0.0.1", 1), ("127.0.0.1", 2)],
            vnodes=32, epoch=3, spares=["127.0.0.1:9", "127.0.0.1:10"],
        )
        m2 = ShardMap.from_json(m.to_json())
        assert m2.epoch == 3
        assert m2.spares == [("127.0.0.1", 9), ("127.0.0.1", 10)]
        for i in range(100):
            k = f"key/{i}".encode()
            assert m.shard_for(k) == m2.shard_for(k)
        # pre-epoch maps (older control planes) parse as epoch 0, no spares
        legacy = ShardMap.from_json(
            json.dumps({"endpoints": ["h:1", "h:2"], "vnodes": 64})
        )
        assert legacy.epoch == 0 and legacy.spares == []

    def test_with_promoted_keeps_routing_and_consumes_spare(self):
        m = ShardMap(
            [("h", 1), ("h", 2), ("h", 3)], spares=["h:9", "h:10"]
        )
        p = m.with_promoted(1, "h:9")
        assert p.epoch == m.epoch + 1
        assert p.endpoints[1] == ("h", 9)
        assert p.spares == [("h", 10)]
        # the ring is keyed by shard INDEX: swapping an endpoint must not
        # move a single key
        for i in range(500):
            k = f"key/{i}".encode()
            assert m.shard_for(k) == p.shard_for(k)


# -- affinity groups ----------------------------------------------------------


class TestAffinity:
    def test_affinity_token_shapes(self):
        from tpu_resiliency.store import affinity_token

        assert affinity_token(b"rdzv/7/node/a") == b"rdzv/7"
        assert affinity_token(b"rdzv/7/open") == b"rdzv/7"
        assert affinity_token(b"barrier/b1/g2/done") == b"barrier/b1"
        # fixed pointers and non-round keys keep per-key routing
        assert affinity_token(b"rdzv/active_round") is None
        assert affinity_token(b"rdzv/shutdown") is None
        assert affinity_token(b"rdzv/7") is None
        assert affinity_token(b"other/7/x") is None

    def test_round_keys_colocate_on_one_shard(self, shard_group):
        c = shard_group.client()
        idxs = {
            c._shard_idx(k) for k in (
                "rdzv/5/open", "rdzv/5/closed", "rdzv/5/join_count",
                "rdzv/5/node/a", "rdzv/5/node/b", "rdzv/5/result",
                "rdzv/5/done",
            )
        }
        assert len(idxs) == 1
        c.close()

    def test_affinity_handle_ops_and_rejection(self, shard_group):
        from tpu_resiliency.store import AffinityGroup

        c = shard_group.client(timeout=10.0)
        g = c.affinity("rdzv/9")
        assert isinstance(g, AffinityGroup)
        g.set("rdzv/9/open", b"1")
        assert g.get("rdzv/9/open") == b"1"
        assert g.add("rdzv/9/join_count", 1) == 1
        new_len, done = g.append_check(
            "rdzv/9/arrivals", "0,", "rdzv/9/done", b"1", required=1
        )
        assert done and g.get("rdzv/9/done") == b"1"
        with pytest.raises(StoreError):
            g.set("rdzv/8/open", b"1")  # outside the group
        with pytest.raises(StoreError):
            g.wait(["barrier/x/done"], timeout=0.1)
        c.close()

    def test_multi_key_ops_require_colocation(self, shard_group):
        c = shard_group.client(timeout=10.0)
        # append_check across two DIFFERENT affinity groups must be refused
        # loudly (single-shard atomicity cannot hold across shards) ...
        pairs = (
            (f"rdzv/{a}/arrivals", f"rdzv/{b}/done")
            for a in range(32) for b in range(32) if a != b
        )
        for log_key, done_key in pairs:
            if c._shard_idx(log_key) != c._shard_idx(done_key):
                with pytest.raises(StoreError):
                    c.append_check(log_key, "0,", done_key, b"1", required=99)
                break
        else:
            pytest.skip("all probed rounds co-hashed (tiny fleet)")
        # ... while same-group pairs work
        _, done = c.append_check(
            "rdzv/3/arrivals", "0,", "rdzv/3/done", b"1", required=1
        )
        assert done
        c.close()

    def test_parallel_wait_spans_shards_within_deadline(self, shard_group):
        c = shard_group.client(timeout=10.0)
        keys = [f"pw/{i}" for i in range(12)]  # spreads over all 4 shards
        assert len({c._shard_idx(k) for k in keys}) > 1

        def setter():
            time.sleep(0.8)
            s = shard_group.client()
            s.multi_set({k: b"1" for k in keys})
            s.close()

        t = threading.Thread(target=setter)
        t.start()
        t0 = time.monotonic()
        c.wait(keys, timeout=10.0)
        elapsed = time.monotonic() - t0
        t.join()
        # per-shard fences ran concurrently: the fence costs ~the setter
        # delay, not a serial accumulation of it across shards
        assert elapsed < 5.0, elapsed
        # and a multi-shard timeout is honored as ONE budget, not per shard
        t0 = time.monotonic()
        with pytest.raises(StoreTimeout):
            c.wait([f"pw/never/{i}" for i in range(8)], timeout=0.6)
        assert time.monotonic() - t0 < 4.0
        c.close()


# -- spare promotion: epoch-bumped failover to a FRESH endpoint ---------------


class TestSparePromotion:
    def test_sigkill_promote_and_inflight_ops_recover(self, tmp_path):
        """The acceptance gate: SIGKILL a shard, promote a spare on a NEW
        port (CAS'd epoch bump, journal-restored), and in-flight WAIT and
        COMPARE_SET ride their existing failover episodes onto the spare —
        the dead endpoint is never reused."""
        from tpu_resiliency.store import promote_spare
        from tpu_resiliency.store.sharding import SHARD_MAP_KEY

        ports = [free_port(), free_port()]
        spare_port = free_port()
        journals = [str(tmp_path / f"pj{i}") for i in range(2)]
        procs = [
            spawn_shard_subprocess(p, journal=j)
            for p, j in zip(ports, journals)
        ]
        spare_proc = None
        endpoints = [f"127.0.0.1:{p}" for p in ports]
        spare_ep = f"127.0.0.1:{spare_port}"
        try:
            seed = StoreClient("127.0.0.1", ports[0], timeout=10.0)
            seed.set(SHARD_MAP_KEY, ShardMap(endpoints, spares=[spare_ep]).to_json())
            c = ShardedStoreClient.from_bootstrap(
                "127.0.0.1", ports[0], timeout=60.0
            )
            assert c.map.spares == [("127.0.0.1", spare_port)]
            victim = c.map.shard_for(b"promo/key")
            c.set("promo/seeded", b"1")  # lands somewhere; journaled if on victim

            waited = {}

            def block():
                try:
                    c.wait(["promo/key"], timeout=90.0)
                    waited["ok"] = True
                except Exception as exc:  # noqa: BLE001
                    waited["err"] = exc

            t = threading.Thread(target=block)
            t.start()
            time.sleep(0.5)  # parked on the doomed shard
            procs[victim].send_signal(signal.SIGKILL)
            procs[victim].wait(timeout=10)

            # the watchdog's moves: spare on a FRESH port, victim's journal
            spare_proc = spawn_shard_subprocess(
                spare_port, journal=journals[victim]
            )
            map_client = StoreClient(
                "127.0.0.1", spare_port if victim == 0 else ports[0],
                timeout=10.0,
            )
            promoted = promote_spare(map_client, victim, spare_ep)
            map_client.close()
            assert promoted.epoch == 1
            assert promoted.endpoints[victim] == ("127.0.0.1", spare_port)
            assert promoted.spares == []

            # in-flight CAS from a client that still holds the OLD map rides
            # the failover episode onto the spare (base-client reconnect
            # budget ~10s precedes the episode, hence the generous timeout)
            ok, v = c.compare_set_ex("promo/key", b"", b"claimed")
            assert ok and v == b"claimed"
            t.join(timeout=60)
            assert waited.get("ok"), waited
            # the client adopted the bumped map: fresh endpoint, no reuse
            assert c.map.epoch == 1
            assert c.endpoints[victim] == ("127.0.0.1", spare_port)
            c.close()
        finally:
            for p in procs:
                p.kill()
            if spare_proc is not None:
                spare_proc.kill()

    def test_bootstrap_via_spare_when_seed_dead(self, tmp_path):
        """A client whose map names spares can rediscover the bumped map
        from a spare endpoint even when its cached shard endpoint is gone."""
        from tpu_resiliency.store import promote_spare
        from tpu_resiliency.store.sharding import SHARD_MAP_KEY

        port, spare_port = free_port(), free_port()
        journal = str(tmp_path / "bj0")
        proc = spawn_shard_subprocess(port, journal=journal)
        spare_ep = f"127.0.0.1:{spare_port}"
        spare_proc = None
        try:
            seed = StoreClient("127.0.0.1", port, timeout=10.0)
            seed.set(
                SHARD_MAP_KEY,
                ShardMap([f"127.0.0.1:{port}"], spares=[spare_ep]).to_json(),
            )
            c = ShardedStoreClient.from_bootstrap("127.0.0.1", port, timeout=45.0)
            c.set("b/x", b"1")
            seed.close()
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
            spare_proc = spawn_shard_subprocess(spare_port, journal=journal)
            mc = StoreClient("127.0.0.1", spare_port, timeout=10.0)
            promote_spare(mc, 0, spare_ep)
            mc.close()
            # every cached endpoint is dead; discovery must fall through to
            # the map's spare list
            assert c.get("b/x", timeout=40.0) == b"1"
            assert c.map.epoch == 1
            c.close()
        finally:
            proc.kill()
            if spare_proc is not None:
                spare_proc.kill()
