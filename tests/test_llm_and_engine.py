"""LLM attribution backend + analysis engine tests.

Reference analog: ``tests/attribution/unit`` (golden outputs over the
LogSage/engine stack).  An in-process fake OpenAI-compatible server stands in
for the real endpoint; the attrsvc e2e drives /submit → /result with all
three analyses in one submission.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tpu_resiliency.attribution import (
    AnalysisEngine,
    AnalysisSpec,
    AttributionResult,
    FailureCategory,
    LLMClient,
    LogAnalyzer,
    default_engine,
    llm_from_env,
)
from tpu_resiliency.attribution.llm import (
    LLMError,
    build_attribution_prompt,
    parse_attribution_response,
)


class FakeOpenAI(BaseHTTPRequestHandler):
    """OpenAI-compatible /chat/completions returning a canned verdict; the
    response content is settable per server instance, and requests are
    recorded for prompt assertions."""

    def do_POST(self):
        n = int(self.headers.get("Content-Length", "0"))
        body = json.loads(self.rfile.read(n).decode())
        self.server.requests.append(body)
        if self.server.fail_times > 0:
            self.server.fail_times -= 1
            self.send_response(500)
            self.end_headers()
            return
        content = self.server.reply
        raw = json.dumps(
            {"choices": [{"message": {"role": "assistant", "content": content}}]}
        ).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def log_message(self, *a):
        pass


@pytest.fixture
def fake_llm_server():
    server = ThreadingHTTPServer(("127.0.0.1", 0), FakeOpenAI)
    server.requests = []
    server.fail_times = 0
    server.reply = json.dumps(
        {
            "category": "network",
            "should_resume": True,
            "confidence": 0.9,
            "culprit_ranks": [5],
            "reason": "DCN link flap on host 5",
        }
    )
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield server
    server.shutdown()
    server.server_close()


def test_llm_client_roundtrip(fake_llm_server):
    client = LLMClient(
        base_url=f"http://127.0.0.1:{fake_llm_server.server_port}",
        api_key="sk-test", model="attr-1",
    )
    out = client("why did it fail?")
    assert "DCN link flap" in out
    req = fake_llm_server.requests[0]
    assert req["model"] == "attr-1"
    assert req["messages"][1]["content"] == "why did it fail?"


def test_llm_client_retries_then_raises(fake_llm_server):
    client = LLMClient(
        base_url=f"http://127.0.0.1:{fake_llm_server.server_port}",
        max_retries=1,
    )
    fake_llm_server.fail_times = 1
    assert "DCN" in client("q")  # one failure absorbed by retry
    fake_llm_server.fail_times = 10
    with pytest.raises(LLMError):
        client("q")


def test_llm_from_env(monkeypatch, fake_llm_server):
    monkeypatch.delenv("TPURX_LLM_BASE_URL", raising=False)
    assert llm_from_env() is None
    monkeypatch.setenv(
        "TPURX_LLM_BASE_URL", f"http://127.0.0.1:{fake_llm_server.server_port}"
    )
    monkeypatch.setenv("TPURX_LLM_MODEL", "m2")
    client = llm_from_env()
    assert client is not None and client.model == "m2"
    assert "DCN" in client("q")


def test_parse_attribution_response_robust():
    assert parse_attribution_response("no json here") is None
    assert parse_attribution_response('{"nope": 1}') is None
    out = parse_attribution_response(
        'Here you go:\n```json\n{"category": "OOM_HBM", "should_resume": false,'
        ' "confidence": 1.7, "culprit_ranks": [2, 2.0], "reason": "hbm"}\n```'
    )
    assert out["category"] == "oom_hbm"
    assert out["confidence"] == 1.0  # clamped
    assert out["culprit_ranks"] == [2, 2]
    assert out["should_resume"] is False
    # mistyped-but-valid JSON is salvaged, not raised on
    out = parse_attribution_response(
        '{"category": "network", "culprit_ranks": null, "confidence": "high"}'
    )
    assert out["category"] == "network"
    assert out["culprit_ranks"] == []
    assert out["confidence"] == 0.5


def test_prompt_carries_rule_verdict():
    p = build_attribution_prompt(
        [(3, "some error line")],
        rule_verdict={"category": "network", "confidence": 0.8},
    )
    assert "L3: some error line" in p
    assert '"network"' in p and "confirm or override" in p


def test_analyzer_llm_always_confirms_and_overrides():
    # concur: same category -> confidence boost + merged ranks
    concur = lambda prompt: json.dumps(
        {"category": "network", "should_resume": True, "confidence": 0.9,
         "culprit_ranks": [7], "reason": "socket reset storm"}
    )
    v = LogAnalyzer(llm_fn=concur, consult_llm="always").analyze_text(
        "[r3] ConnectionResetError: peer gone\n"
    )
    assert v.category == FailureCategory.NETWORK
    assert v.confidence > 0.8
    assert v.culprit_ranks == [3, 7]
    # override: different category, higher confidence than the rules
    override = lambda prompt: json.dumps(
        {"category": "preemption", "should_resume": True, "confidence": 0.97,
         "culprit_ranks": [], "reason": "maintenance event"}
    )
    v2 = LogAnalyzer(llm_fn=override, consult_llm="always").analyze_text(
        "[r3] ConnectionResetError: peer gone\n"
    )
    assert v2.category == FailureCategory.PREEMPTION
    assert "overrode" in v2.summary
    # never: llm_fn present but not consulted
    calls = []
    v3 = LogAnalyzer(
        llm_fn=lambda p: calls.append(p), consult_llm="never"
    ).analyze_text("[r3] ConnectionResetError: peer gone\n")
    assert v3.category == FailureCategory.NETWORK and not calls


def test_analyzer_survives_llm_garbage():
    v = LogAnalyzer(llm_fn=lambda p: "%%% not json", consult_llm="always").analyze_text(
        "RESOURCE_EXHAUSTED: out of HBM memory\n"
    )
    assert v.category == FailureCategory.OOM_HBM  # rules verdict stands
    assert v.should_resume is False


# -- engine -------------------------------------------------------------------


def _markers(stale_rank=2, n=4):
    now = time.time()
    return {
        str(r): {
            "rank": r,
            "iteration": 0,
            "step": 100 if r != stale_rank else 37,
            "phase": "step",
            "ts": now if r != stale_rank else now - 120,
        }
        for r in range(n)
    }


def test_engine_runs_dag_and_reuses_results():
    calls = []

    def log_fn(payload, upstream, ctx):
        calls.append("log")
        return AttributionResult(category="network", confidence=0.8)

    def trace_fn(payload, upstream, ctx):
        calls.append("trace")
        return AttributionResult(category="lagging", confidence=0.6, culprit_ranks=[2])

    def joint_fn(payload, upstream, ctx):
        calls.append("joint")
        assert set(upstream) == {"l", "t"}  # upstream RESULTS, not recompute
        return AttributionResult(
            category="joint", confidence=0.9,
            culprit_ranks=upstream["t"].culprit_ranks,
        )

    eng = AnalysisEngine(
        [
            AnalysisSpec(name="l", fn=log_fn),
            AnalysisSpec(name="t", fn=trace_fn),
            AnalysisSpec(name="j", fn=joint_fn, depends_on=["l", "t"]),
        ]
    )
    out = eng.run_all({"x": 1})
    assert out["done"] and not out["errors"]
    assert out["results"]["j"]["culprit_ranks"] == [2]
    assert calls.count("log") == 1 and calls.count("trace") == 1
    eng.shutdown()


def test_engine_isolates_failures_and_skips():
    def boom(payload, upstream, ctx):
        raise RuntimeError("kaput")

    def dependent(payload, upstream, ctx):
        return AttributionResult(category="x", confidence=1.0)

    eng = AnalysisEngine(
        [
            AnalysisSpec(name="a", fn=boom),
            AnalysisSpec(name="b", fn=dependent, depends_on=["a"]),
            AnalysisSpec(
                name="c", fn=dependent, applicable=lambda p: False
            ),
        ]
    )
    out = eng.run_all({})
    assert "kaput" in out["errors"]["a"]
    assert out["errors"]["b"] == "upstream analysis failed"
    assert out["skipped"] == ["c"]
    eng.shutdown()


def test_engine_survives_raising_applicable():
    # a user predicate that raises must surface as that analysis's error —
    # not kill the job runner and report a silently-empty done job
    def ok_fn(payload, upstream, ctx):
        return AttributionResult(category="network", confidence=0.5)

    eng = AnalysisEngine(
        [
            AnalysisSpec(name="bad", fn=ok_fn,
                         applicable=lambda p: p["missing"] is not None),
            AnalysisSpec(name="good", fn=ok_fn),
        ]
    )
    out = eng.run_all({})
    assert out["done"]
    assert "applicable() raised" in out["errors"]["bad"]
    assert "good" in out["results"]


def test_parse_markers_validation():
    from tpu_resiliency.attribution.trace_analyzer import parse_markers

    assert parse_markers(None) == {}
    parsed = parse_markers({"3": None, "1": {"rank": 1, "iteration": 0, "step": 5}})
    assert parsed[3] is None and parsed[1].step == 5
    with pytest.raises(ValueError):
        parse_markers("not a dict")
    with pytest.raises(ValueError):
        parse_markers({"x": None})
    with pytest.raises(ValueError):
        parse_markers({"1": {"bogus": 1}})
    with pytest.raises(ValueError):
        parse_markers({"1": 42})


def test_default_engine_three_analyses():
    eng = default_engine()
    out = eng.run_all(
        {
            "text": "[r2] RESOURCE_EXHAUSTED: out of HBM memory\n",
            "markers": _markers(stale_rank=2),
            "stale_after_s": 30.0,
        }
    )
    assert set(out["results"]) == {"log", "trace", "combined"}
    assert out["results"]["log"]["category"] == "oom_hbm"
    assert 2 in out["results"]["trace"]["culprit_ranks"]
    combined = out["results"]["combined"]
    assert combined["should_resume"] is False  # OOM dominates the trace
    assert 2 in combined["culprit_ranks"]
    eng.shutdown()


def test_default_engine_skips_without_inputs():
    eng = default_engine()
    out = eng.run_all({"text": "Traceback (most recent call last)\n"})
    assert "log" in out["results"]
    assert "trace" in out["skipped"] and "combined" in out["skipped"]
    eng.shutdown()


# -- attrsvc e2e --------------------------------------------------------------


def test_attrsvc_submit_e2e(monkeypatch, fake_llm_server):
    import importlib
    import urllib.request

    monkeypatch.setenv(
        "TPURX_LLM_BASE_URL", f"http://127.0.0.1:{fake_llm_server.server_port}"
    )
    from tpu_resiliency.services import attrsvc as svc

    importlib.reload(svc)  # rebuild STATE with the env-configured LLM
    assert svc.STATE.llm_fn is not None
    server = svc.serve(host="127.0.0.1", port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{server.server_port}"

    def post(path, payload):
        req = urllib.request.Request(
            base + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read().decode())

    try:
        # no rule matches -> the fake LLM decides (fallback mode)
        sub = post(
            "/submit",
            {"text": "bizarre error qwerty-77\n", "markers": _markers(stale_rank=1)},
        )
        job_id = sub["job_id"]
        with urllib.request.urlopen(
            f"{base}/result/{job_id}?wait=30", timeout=40
        ) as r:
            out = json.loads(r.read().decode())
        assert out["done"], out
        assert set(out["results"]) == {"log", "trace", "combined"}
        assert out["results"]["log"]["category"] == "network"  # fake LLM verdict
        assert out["results"]["log"]["culprit_ranks"] == [5]
        assert fake_llm_server.requests  # the endpoint was really consulted
        # unknown job id -> 404
        try:
            urllib.request.urlopen(f"{base}/result/nope", timeout=10)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        # stats reflect the job + llm backend
        with urllib.request.urlopen(f"{base}/stats", timeout=10) as r:
            stats = json.loads(r.read().decode())
        assert stats["jobs_submitted"] == 1 and stats["llm_backend"] is True
    finally:
        server.shutdown()
        server.server_close()
        importlib.reload(svc)  # restore module-level STATE without the env
