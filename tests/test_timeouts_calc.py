"""TimeoutsCalc unit tests (reference analog: tests/fault_tolerance/unit/test_timeouts_calc.py)."""

import threading

import pytest

from tpu_resiliency.fault_tolerance.data import HeartbeatTimeouts
from tpu_resiliency.fault_tolerance.timeouts import TimeoutsCalc, TimeoutsCalcError
from tpu_resiliency.store import StoreClient


def test_heartbeat_observation():
    tc = TimeoutsCalc(start_time=100.0, safety_factor=5.0)
    assert not tc.can_get_hb_timeouts
    tc.update_on_heartbeat(now=102.0)   # initial = 2.0
    assert not tc.can_get_hb_timeouts
    tc.update_on_heartbeat(now=103.0)   # subsequent = 1.0
    tc.update_on_heartbeat(now=106.0)   # subsequent = 3.0
    assert tc.can_get_hb_timeouts
    t = tc.calculate_hb_timeouts()
    assert t.initial == pytest.approx(10.0)
    assert t.subsequent == pytest.approx(15.0)
    assert t.were_calculated


def test_hb_timeout_ema_never_shrinks_below_needed():
    tc = TimeoutsCalc(start_time=0.0, safety_factor=2.0, ema_alpha=0.5)
    tc.update_on_heartbeat(now=1.0)
    tc.update_on_heartbeat(now=2.0)
    current = HeartbeatTimeouts(initial=100.0, subsequent=100.0, were_calculated=True)
    t = tc.calculate_hb_timeouts(current)
    # EMA of (2, 100) = 51, and >= 2*observed
    assert t.initial == pytest.approx(51.0)
    # configured (not calculated) timeouts are replaced, not merged
    configured = HeartbeatTimeouts(initial=100.0, subsequent=100.0, were_calculated=False)
    t2 = tc.calculate_hb_timeouts(configured)
    assert t2.initial == pytest.approx(2.0)


def test_sections():
    tc = TimeoutsCalc(start_time=0.0, safety_factor=2.0, sections=("step",))
    tc.update_on_section_start("step", now=5.0)   # out-of-section gap: 5
    tc.update_on_section_end("step", now=7.0)     # step: 2
    tc.update_on_section_start("step", now=8.0)   # oos: 1
    tc.update_on_section_end("step", now=12.0)    # step: 4
    t = tc.calculate_section_timeouts()
    assert t.section["step"] == pytest.approx(8.0)
    assert t.out_of_section == pytest.approx(10.0)
    assert "step" in t.calculated_sections
    with pytest.raises(TimeoutsCalcError):
        tc.update_on_section_end("never-opened")


def test_section_nesting_error():
    tc = TimeoutsCalc(start_time=0.0)
    tc.update_on_section_start("a", now=1.0)
    with pytest.raises(TimeoutsCalcError):
        tc.update_on_section_start("a", now=2.0)


def test_synchronize_all_store_max(store_server):
    world = 3
    results = {}

    def member(rank):
        c = StoreClient("127.0.0.1", store_server.port, timeout=10.0)
        tc = TimeoutsCalc(start_time=0.0, safety_factor=2.0)
        tc.update_on_heartbeat(now=1.0 + rank)        # initial = 1+rank
        tc.update_on_heartbeat(now=1.0 + rank + (rank + 1) * 0.5)  # subseq
        tc.synchronize_all(store=c, rank=rank, world_size=world)
        results[rank] = (tc.initial_max, tc.subsequent_max)
        c.close()

    threads = [threading.Thread(target=member, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # all ranks converge on the global max
    assert all(results[r] == results[0] for r in range(world))
    assert results[0][0] == pytest.approx(3.0)   # max initial
    assert results[0][1] == pytest.approx(1.5)   # max subsequent


def test_synchronize_all_reduce_fn():
    tc = TimeoutsCalc(start_time=0.0)
    tc.update_on_heartbeat(now=2.0)
    tc.synchronize_all(reduce_fn=lambda vals: {k: v * 10 for k, v in vals.items()})
    assert tc.initial_max == pytest.approx(20.0)


def test_synchronize_all_disjoint_sections(store_server):
    """Ranks that observed different section sets merge by key union."""
    results = {}

    def member(rank, section):
        c = StoreClient("127.0.0.1", store_server.port, timeout=10.0)
        tc = TimeoutsCalc(start_time=0.0, safety_factor=2.0)
        tc.update_on_section_start(section, now=1.0)
        tc.update_on_section_end(section, now=1.0 + (rank + 1))
        tc.synchronize_all(store=c, rank=rank, world_size=2)
        results[rank] = dict(tc.section_max)
        c.close()

    threads = [
        threading.Thread(target=member, args=(0, "fwd")),
        threading.Thread(target=member, args=(1, "bwd")),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for r in (0, 1):
        assert results[r]["fwd"] == pytest.approx(1.0)
        assert results[r]["bwd"] == pytest.approx(2.0)
