"""Every example script stays runnable (the reference ships its examples as
living documentation; broken examples are worse than none)."""

import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

from tests.test_launcher import free_port

REPO = Path(__file__).resolve().parent.parent
EXAMPLES = REPO / "examples"


def _scrub_env(env):
    """Force subprocesses onto pure CPU: the axon sitecustomize would
    otherwise re-select the (possibly absent) TPU platform in the child."""
    from tpu_resiliency.utils.env import disarm_platform_sitecustomize

    return disarm_platform_sitecustomize(env)


def _run(script, env_extra=None, timeout=180, args=()):
    env = _scrub_env(dict(os.environ))
    env["TPURX_REPO"] = str(REPO)
    env.update(env_extra or {})
    out = subprocess.run(
        [sys.executable, str(script), *args],
        cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=timeout,
    )
    assert out.returncode == 0, (
        f"{script} rc={out.returncode}\n{out.stdout[-1500:]}\n{out.stderr[-1500:]}"
    )
    return out


def test_attribution_example():
    out = _run(EXAMPLES / "attribution" / "single_server_example.py")
    assert "category:      oom_hbm" in out.stdout
    assert "should_resume: False" in out.stdout


def test_async_ckpt_example():
    out = _run(EXAMPLES / "checkpointing" / "async_ckpt.py")
    assert "async checkpoint roundtrip OK" in out.stdout


def test_local_ckpt_example():
    out = _run(EXAMPLES / "checkpointing" / "local_ckpt.py")
    assert "recovered from clique buddy" in out.stdout


def test_straggler_example():
    out = _run(EXAMPLES / "straggler" / "example.py")
    assert "always-on collector: 16 samples" in out.stdout


def test_health_example():
    out = _run(EXAMPLES / "utils" / "node_health_check_example.py")
    assert "node is" in out.stdout  # healthy or not — runs either way


def test_inprocess_basic_example(store_server):
    env = {
        "TPURX_STORE_ADDR": "127.0.0.1",
        "TPURX_STORE_PORT": str(store_server.port),
        "TPURX_WORLD_SIZE": "2",
    }
    procs = []
    try:
        for r in range(2):
            e = _scrub_env(
                dict(os.environ, TPURX_REPO=str(REPO), TPURX_RANK=str(r), **env)
            )
            procs.append(subprocess.Popen(
                [sys.executable,
                 str(EXAMPLES / "inprocess" / "basic_example.py")],
                cwd=str(REPO), env=e, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            ))
        outs = [p.communicate(timeout=180)[0] for p in procs]
    finally:
        for p in procs:  # never leak children on timeout/assert failure
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-1500:]
        assert "result: ok@1" in out, out[-1500:]  # restarted past the fault


def test_inprocess_advanced_example(store_server):
    env = {
        "TPURX_STORE_ADDR": "127.0.0.1",
        "TPURX_STORE_PORT": str(store_server.port),
        "TPURX_RANK": "0",
        "TPURX_WORLD_SIZE": "1",
    }
    out = _run(EXAMPLES / "inprocess" / "advanced_example.py", env_extra=env)
    assert "result: done" in out.stdout


@pytest.mark.parametrize("script,cfg", [
    ("basic_ft_example.py", None),
    ("sections_example.py", "ft_cfg_sections.yaml"),
])
def test_ft_examples_under_launcher(tmp_path, script, cfg):
    env = _scrub_env(dict(os.environ))
    env.update({
        "TPURX_REPO": str(REPO),
        "TPURX_FT_ENABLE_DEVICE_HEALTH_CHECK": "0",
        "FT_STATE": str(tmp_path / "state_{}.json"),
    })
    cmd = [
        sys.executable, "-m", "tpu_resiliency.fault_tolerance.launcher",
        "--nnodes", "1", "--nproc-per-node", "2", "--host-store",
        "--rdzv-endpoint", f"127.0.0.1:{free_port()}",
    ]
    if cfg:
        cmd += ["--ft-cfg", str(EXAMPLES / "fault_tolerance" / cfg)]
    cmd += ["--", str(EXAMPLES / "fault_tolerance" / script)]
    out = subprocess.run(
        cmd, cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=180,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "done" in out.stdout
