"""Unit surface of the self-healing collective layer (docs/collectives.md).

Pure-Python lanes (no jax, no mesh): the wrapper's contract — deadline
trips typed with op+axis, the retry → relayout → shrink ladder, route
health bias, at-abort DegradeVerdict consumption — is all host-side
machinery exercised here with plain callables.  The end-to-end drives
live in test_soak_smoke.py (--link-degrade campaign) and
test_layered_restart.py ("degrade" scenario).
"""

import threading
import time

import pytest

from tpu_resiliency.attribution.base import AttributionResult
from tpu_resiliency.attribution.trace_analyzer import (
    DegradeVerdict,
    analyze_fingerprints,
    degrade_verdict,
)
from tpu_resiliency.inprocess.abort import (
    AbortLadder,
    DegradeToShrink,
    ShrinkMeshStage,
    get_degrade_hook,
    install_degrade_hook,
)
from tpu_resiliency.parallel import collectives as coll_mod
from tpu_resiliency.parallel.collectives import (
    ResilientCollective,
    wrap_collective,
)
from tpu_resiliency.parallel.deadline import CollectiveTimeout, DeadlineLane
from tpu_resiliency.parallel.degrade import DegradePolicy, trip_shrink
from tpu_resiliency.parallel.health import SUSPECT_AFTER, health


@pytest.fixture(autouse=True)
def fresh_collective_plane():
    """Each test gets its own shared lane + route-health registry (the
    singletons are process-global; a tripped route from one test must not
    bias the next)."""
    coll_mod._reset_for_tests()
    install_degrade_hook(None)
    yield
    coll_mod._reset_for_tests()
    install_degrade_hook(None)


def sleeper(seconds, value):
    def fn(*args, **kwargs):
        time.sleep(seconds)
        return value

    return fn


# -- wrapper basics ----------------------------------------------------------


def test_wrapped_op_returns_primary_result_and_args_pass_through():
    calls = []

    def op(a, b, *, k=0):
        calls.append((a, b, k))
        return a + b + k

    c = wrap_collective(op, "add_op", axis="data", deadline_ms=5000.0)
    assert c(1, 2, k=3) == 6
    assert calls == [(1, 2, 3)]
    st = health().route("add_op", "data")
    assert st.ok_count == 1 and st.timeout_count == 0
    assert st.ewma_latency_ns > 0


def test_zero_budget_runs_inline_on_caller_thread():
    seen = {}

    def op():
        seen["thread"] = threading.current_thread()
        return 42

    c = ResilientCollective("inline_op", op, deadline_ms=0.0)
    assert c() == 42
    # the opt-out: no worker handoff at all
    assert seen["thread"] is threading.current_thread()


def test_op_exception_propagates_untouched():
    def op():
        raise ValueError("not a hang")

    c = ResilientCollective(
        "raiser", op, deadline_ms=5000.0,
        policy=DegradePolicy(rungs=(), retries=0),
    )
    with pytest.raises(ValueError, match="not a hang"):
        c()
    # an op *failure* is not a deadline trip
    assert health().route("raiser", "").timeout_count == 0


def test_env_knobs_read_at_call_time(monkeypatch):
    c = ResilientCollective("knobbed", lambda: 1)
    monkeypatch.setenv("TPURX_COLL_DEADLINE_MS", "123.5")
    assert c.budget_ms() == 123.5
    monkeypatch.setenv("TPURX_COLL_RETRIES", "7")
    monkeypatch.setenv("TPURX_COLL_DEGRADE", "retry,shrink,bogus")
    pol = c.policy()
    assert pol.retries == 7
    assert pol.rungs == ("retry", "shrink")  # unknown rung dropped


# -- deadline trips ----------------------------------------------------------


def test_deadline_trip_raises_typed_timeout_naming_op_and_axis():
    c = ResilientCollective(
        "slow_gather", sleeper(0.6, "late"), axis="model",
        deadline_ms=100.0, policy=DegradePolicy(rungs=(), retries=0),
    )
    with pytest.raises(CollectiveTimeout) as ei:
        c()
    exc = ei.value
    assert exc.op == "slow_gather"
    assert exc.axis == "model"
    assert exc.budget_ms == 100.0
    assert "collective 'slow_gather' exceeded its 100ms deadline" in str(exc)
    assert "mesh axis 'model'" in str(exc)
    st = health().route("slow_gather", "model")
    assert st.timeout_count == 1 and st.consecutive_timeouts == 1


def test_lane_abandons_worker_and_serves_next_op():
    lane = DeadlineLane("t-abandon")
    try:
        with pytest.raises(CollectiveTimeout):
            lane.run(sleeper(0.6, None), op="wedged", budget_ms=80.0)
        assert lane.abandoned == 1
        # a fresh worker serves the next submission immediately — the lane
        # is not poisoned by the still-sleeping abandoned thread
        assert lane.run(lambda: "ok", op="next", budget_ms=2000.0) == "ok"
    finally:
        lane.stop()


def test_retry_rung_absorbs_transient_stall():
    attempts = []

    def flaky():
        attempts.append(time.monotonic())
        if len(attempts) == 1:
            time.sleep(0.5)  # first call blows the budget (transient)
        return "recovered"

    c = ResilientCollective(
        "flaky_op", flaky, deadline_ms=120.0,
        policy=DegradePolicy(rungs=("retry",), retries=2),
    )
    assert c() == "recovered"
    assert len(attempts) == 2
    st = health().route("flaky_op", "")
    # recovered via retry: no lasting route bias
    assert st.consecutive_timeouts == 0
    assert health().start_rung("flaky_op", "") == ""


# -- degrade ladder ----------------------------------------------------------


def test_relayout_rung_lands_on_fallback_and_biases_route():
    primary_calls, relayouts = [], []

    def primary():
        primary_calls.append(1)
        time.sleep(0.5)  # a dead link: every primary attempt blows budget
        return "primary"

    c = ResilientCollective(
        "dead_link", primary, axis="data", fallback=lambda: "via_fallback",
        deadline_ms=100.0,
        policy=DegradePolicy(rungs=("retry", "relayout"), retries=0),
        relayout=lambda: relayouts.append(1) or "noop",
    )
    assert c() == "via_fallback"
    assert relayouts == [1]
    assert len(primary_calls) == 1  # retries=0: one burned deadline only
    # recovery via relayout biases the route: the next call must NOT burn
    # another deadline re-proving the primary
    assert health().start_rung("dead_link", "data") == "relayout"
    assert c() == "via_fallback"
    assert len(primary_calls) == 1  # primary never re-attempted


def test_consecutive_timeouts_arm_relayout_bias():
    c = ResilientCollective(
        "suspect_link", sleeper(0.4, None), deadline_ms=80.0,
        policy=DegradePolicy(rungs=(), retries=0),
    )
    for _ in range(SUSPECT_AFTER):
        with pytest.raises(CollectiveTimeout):
            c()
    assert health().start_rung("suspect_link", "") == "relayout"
    health().clear_route("suspect_link", "")
    assert health().start_rung("suspect_link", "") == ""


def test_exhausted_ladder_reraises_last_timeout():
    c = ResilientCollective(
        "hopeless", sleeper(0.4, None), axis="x", deadline_ms=80.0,
        fallback=sleeper(0.6, None),  # the fallback lane is dead too
        policy=DegradePolicy(rungs=("retry", "relayout"), retries=0),
        relayout=lambda: "noop",
    )
    with pytest.raises(CollectiveTimeout) as ei:
        c()
    assert ei.value.op == "hopeless"


class RecordingHook:
    """Stand-in degrade hook (the real DegradeToShrink tears down jax
    backends — not for a unit lane)."""

    def __init__(self):
        self.calls = []

    def __call__(self, op="", axis="", culprits=()):
        self.calls.append((op, axis, tuple(culprits)))
        return "recorded"


def test_shrink_rung_fires_installed_degrade_hook():
    hook = RecordingHook()
    install_degrade_hook(hook)
    assert get_degrade_hook() is hook
    c = ResilientCollective(
        "shrink_me", sleeper(0.5, None), axis="ici",
        fallback=lambda: "post_shrink", deadline_ms=90.0,
        policy=DegradePolicy(rungs=("shrink",), retries=0),
    )
    assert c() == "post_shrink"
    assert hook.calls == [("shrink_me", "ici", ())]


def test_trip_shrink_without_hook_runs_bare_ladder_gated_off():
    # standalone process (no wrapper installed a hook): trip_shrink builds
    # a one-rung ladder around ShrinkMeshStage, which is opt-in and —
    # TPURX_SHRINK_MESH unset here — gates itself off (outcome recorded,
    # no backend teardown)
    detail = trip_shrink("lone_op", "axis0")
    assert "shrink_mesh=skipped" in detail


def test_degrade_to_shrink_runs_shrink_stage_through_ladder_accounting():
    ladder = AbortLadder(ShrinkMeshStage(enabled=False), name="degrade")
    hook = DegradeToShrink(ladder)
    out = hook(op="opx", axis="ax", culprits=(3,))
    assert hook.trips == 1
    assert "shrink_mesh=skipped" in out  # gated stage: outcome still recorded


# -- at-abort verdict consumption --------------------------------------------


def _laggard_tails():
    """Synthetic at-abort fingerprints: ranks 0/2 parked fresh inside
    'unified_allreduce', rank 1 stopped dispatching long before them."""
    return {
        0: [{"op": "unified_allreduce", "age_ms": 50.0, "seq": 10}],
        1: [{"op": "unified_allreduce", "age_ms": 5000.0, "seq": 10}],
        2: [{"op": "unified_allreduce", "age_ms": 60.0, "seq": 10}],
    }


def test_degrade_verdict_maps_wedged_collective_to_shrink():
    result = analyze_fingerprints(_laggard_tails())
    assert result.category == "wedged_collective"
    dv = degrade_verdict(result)
    assert dv.action == "shrink"
    assert dv.op == "unified_allreduce"
    assert dv.culprit_ranks == [1]
    # machine-readable: survives the store round-trip
    assert DegradeVerdict.from_json(dv.to_json()) == dv


def test_degrade_verdict_maps_pod_wide_stall_to_relayout():
    result = AttributionResult(
        category="collective_stall", confidence=0.5,
        summary="pod-wide", extra={"op": "ring_permute"},
    )
    dv = degrade_verdict(result)
    assert dv.action == "relayout" and dv.op == "ring_permute"


def test_degrade_verdict_none_for_non_collective_categories():
    dv = degrade_verdict(
        AttributionResult(category="no_data", confidence=0.0, summary="")
    )
    assert dv.action == "none"
    health().apply_verdict(dv)  # a none-verdict must not arm anything
    assert health().start_rung("", "") == ""


def test_applied_verdict_pre_arms_route_and_first_call_starts_at_rung():
    dv = degrade_verdict(analyze_fingerprints(_laggard_tails()))
    health().apply_verdict(dv)
    assert health().start_rung("unified_allreduce", "") == "shrink"

    hook = RecordingHook()
    install_degrade_hook(hook)
    primary_calls = []

    def primary():
        primary_calls.append(1)
        return "healthy"

    c = ResilientCollective(
        "unified_allreduce", primary, fallback=lambda: "degraded",
        deadline_ms=5000.0,
        policy=DegradePolicy(rungs=("retry", "relayout", "shrink"), retries=2),
    )
    # the pre-armed route starts the ladder AT the shrink rung: the primary
    # attempt (known-doomed per the verdict) is never burned
    assert c() == "degraded"
    assert primary_calls == []
    assert hook.calls and hook.calls[0][0] == "unified_allreduce"
