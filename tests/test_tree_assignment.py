"""Multi-layer ``Tree`` rank assignment (reference
``inprocess/rank_assignment.py:416-520``): init activation bounded by
``max_ranks``, ``min_ranks`` branch termination, RESERVE promotion,
BACKFILL local gap-filling, global shift, ``world_size_filter``.

All pure logic — one ``Tree`` instance per rank is driven through the same
cumulative terminated sets the store would serve, and every step asserts
cross-rank consistency (unique app ranks 0..A-1, agreed active world size).
"""

import pytest

from tpu_resiliency.inprocess import (
    Layer,
    LayerFlag,
    Mode,
    RankAssignmentCtx,
    RestartAbort,
    Tree,
    tpu_pod_layers,
)
from tpu_resiliency.inprocess.rank_assignment import RankDiscontinued
from tpu_resiliency.inprocess.state import State

DISCONTINUED = "discontinued"


def host_layers(chips=4, max_active=None, root_flag=LayerFlag.RESERVE,
                host_flag=LayerFlag.RESERVE, host_min=None, host_max=None,
                root_min=1):
    return [
        Layer(min_ranks=root_min, max_ranks=max_active, key_of_rank="root",
              flag=root_flag),
        Layer(min_ranks=chips if host_min is None else host_min,
              max_ranks=chips if host_max is None else host_max,
              key_of_rank=lambda r, c=chips: r // c, flag=host_flag),
    ]


def simulate(world, layers_fn, term_steps, world_size_filter=None):
    """Drive one Tree per rank through a cumulative ordered termination log
    (what ``InprocStore.terminated_ranks()`` serves); return per-step
    snapshots {initial_rank: State | DISCONTINUED}."""
    trees = {
        r: Tree(layers_fn(), world_size_filter=world_size_filter)
        for r in range(world)
    }
    alive = set(range(world))
    log = []  # ordered, like the store's append log
    steps = []
    for terms in term_steps:
        log.extend(t for t in terms if t not in log)
        snap = {}
        for r in sorted(alive - set(log)):
            st = State(rank=r, world_size=world)
            try:
                trees[r](RankAssignmentCtx(st, list(log)))
                snap[r] = st
            except RankDiscontinued:
                alive.discard(r)
                snap[r] = DISCONTINUED
        steps.append(snap)
        check_consistency(snap)
    return steps


def check_consistency(snap):
    states = [s for s in snap.values() if s is not DISCONTINUED]
    if not states:
        return
    active_worlds = {s.active_world_size for s in states}
    worlds = {s.world_size for s in states}
    assert len(active_worlds) == 1, f"disagree on active world: {active_worlds}"
    assert len(worlds) == 1, f"disagree on world: {worlds}"
    actives = sorted(s.rank for s in states if s.mode is Mode.ACTIVE)
    assert actives == list(range(len(actives))), f"active ranks not 0..A-1: {actives}"
    assert len(actives) == active_worlds.pop()
    all_ranks = [s.rank for s in states]
    assert len(all_ranks) == len(set(all_ranks)), f"duplicate ranks: {all_ranks}"


def active_map(snap):
    """initial_rank -> app rank, actives only."""
    return {
        r: s.rank
        for r, s in snap.items()
        if s is not DISCONTINUED and s.mode is Mode.ACTIVE
    }


class TestInitActivation:
    def test_all_active_no_cap(self):
        (snap,) = simulate(8, lambda: host_layers(4), [()])
        assert active_map(snap) == {r: r for r in range(8)}

    def test_root_max_active_parks_surplus(self):
        (snap,) = simulate(8, lambda: host_layers(4, max_active=4), [()])
        assert active_map(snap) == {0: 0, 1: 1, 2: 2, 3: 3}
        for r in (4, 5, 6, 7):
            assert snap[r].mode is Mode.INACTIVE
            assert snap[r].active_rank is None

    def test_host_max_ranks_limits_per_host(self):
        (snap,) = simulate(
            8, lambda: host_layers(4, host_min=1, host_max=2), [()]
        )
        # two actives per 4-chip host, in DFS order
        assert active_map(snap) == {0: 0, 1: 1, 4: 2, 5: 3}

    def test_parked_ranks_numbered_after_actives(self):
        (snap,) = simulate(8, lambda: host_layers(4, max_active=4), [()])
        parked = sorted(s.rank for s in snap.values() if s.mode is Mode.INACTIVE)
        assert parked == [4, 5, 6, 7]


class TestMinRanksTermination:
    def test_partial_host_terminates_whole_host(self):
        steps = simulate(8, lambda: host_layers(4, max_active=None), [(), (5,)])
        snap = steps[1]
        for r in (4, 6, 7):
            assert snap[r] is DISCONTINUED
        assert active_map(snap) == {r: r for r in range(4)}
        assert snap[0].world_size == 4

    def test_root_min_ranks_aborts_everyone(self):
        steps = simulate(
            8, lambda: host_layers(4, root_min=8, host_min=1), [(), (3,)]
        )
        assert all(v is DISCONTINUED for v in steps[1].values())

    def test_cascading_propagation_host_then_slice(self):
        # chip->host->slice: host loss drops slice below its min -> slice dies
        layers = lambda: tpu_pod_layers(chips_per_host=2, hosts_per_slice=2)
        steps = simulate(8, layers, [(), (0,)])
        snap = steps[1]
        for r in (1, 2, 3):
            assert snap[r] is DISCONTINUED
        assert active_map(snap) == {4: 0, 5: 1, 6: 2, 7: 3}


class TestReservePromotion:
    def test_same_host_spare_takes_gap(self):
        layers = lambda: host_layers(4, host_min=1, host_max=2)
        steps = simulate(8, layers, [(), (1,)])
        # init actives: {0,1} on host0, {4,5} on host1; spare 2 promotes into
        # rank 1's slot (same-host RESERVE scope preferred in DFS order)
        assert active_map(steps[1]) == {0: 0, 2: 1, 4: 2, 5: 3}

    def test_cross_host_promotion_through_reserve_root(self):
        layers = lambda: host_layers(4, max_active=4)
        steps = simulate(8, layers, [(), (1,)])
        # host0 falls below min_ranks=4 -> whole host0 dies -> 4 gaps ->
        # host1 spares promote in order
        snap = steps[1]
        for r in (0, 2, 3):
            assert snap[r] is DISCONTINUED
        assert active_map(snap) == {4: 0, 5: 1, 6: 2, 7: 3}

    def test_search_stops_at_non_reserve_layer(self):
        # host layer NOT flagged RESERVE: the upward search never reaches the
        # (reserve) root, so the host-1 spares stay parked and ranks shift
        layers = lambda: host_layers(
            4, host_min=1, host_max=2, host_flag=LayerFlag.NONE
        )
        steps = simulate(8, layers, [(), (1,)])
        snap = steps[1]
        assert active_map(snap) == {0: 0, 4: 1, 5: 2}
        assert snap[2].mode is Mode.INACTIVE

    def test_candidate_must_respect_own_host_max_ranks(self):
        layers = lambda: host_layers(4, host_min=1, host_max=2)
        # kill host0's actives AND spares -> no same-host candidates; host1
        # is at max_ranks=2 so its spares cannot promote either
        steps = simulate(8, layers, [(), (0, 1, 2, 3)])
        snap = steps[1]
        assert active_map(snap) == {4: 0, 5: 1}
        assert snap[6].mode is Mode.INACTIVE
        assert snap[7].mode is Mode.INACTIVE

    def test_promotion_sequence_exhausts_spares(self):
        layers = lambda: host_layers(4, host_min=1, host_max=2)
        steps = simulate(8, layers, [(), (0,), (1,), (2,), (3,)])
        # spares 2 then 3 promote; afterwards host0 is empty and host1 full
        assert active_map(steps[1]) == {1: 1, 2: 0, 4: 2, 5: 3}
        assert active_map(steps[2]) == {2: 0, 3: 1, 4: 2, 5: 3}
        # no spares left for rank 2's slot (host1 at max_ranks) -> shift
        assert active_map(steps[3]) == {3: 0, 4: 1, 5: 2}
        snap = steps[4]
        assert active_map(snap) == {4: 0, 5: 1}


class TestBuildTimeConstraints:
    def test_min_ranks_enforced_at_build(self):
        # world 6 with 4-chip hosts: the 2-chip remainder host must never
        # activate as an illegal sub-mesh — terminated before activation
        (snap,) = simulate(6, lambda: host_layers(4, host_min=4), [()])
        assert snap[4] is DISCONTINUED and snap[5] is DISCONTINUED
        assert active_map(snap) == {0: 0, 1: 1, 2: 2, 3: 3}


class TestBackfillAndShift:
    def test_backfill_search_stops_at_unflagged_layer(self):
        # root BACKFILL but host NONE: the chain breaks at the host layer,
        # so no cross-host backfill happens — plain shift instead
        layers = lambda: host_layers(
            4, host_min=1, root_flag=LayerFlag.BACKFILL, host_flag=LayerFlag.NONE
        )
        steps = simulate(8, layers, [(), (1,)])
        assert active_map(steps[1]) == {0: 0, 2: 1, 3: 2, 4: 3, 5: 4, 6: 5, 7: 6}

    def test_backfill_moves_largest_local_rank_into_gap(self):
        layers = lambda: host_layers(
            4, host_min=1, root_flag=LayerFlag.NONE, host_flag=LayerFlag.BACKFILL
        )
        steps = simulate(8, layers, [(), (1,)])
        # host0's largest app rank (3) backfills slot 1; ranks 4..7 shift by 1
        assert active_map(steps[1]) == {0: 0, 3: 1, 2: 2, 4: 3, 5: 4, 6: 5, 7: 6}

    def test_plain_shift_without_flags(self):
        layers = lambda: host_layers(
            4, host_min=1, root_flag=LayerFlag.NONE, host_flag=LayerFlag.NONE
        )
        steps = simulate(8, layers, [(), (1,)])
        assert active_map(steps[1]) == {0: 0, 2: 1, 3: 2, 4: 3, 5: 4, 6: 5, 7: 6}


class TestWorldSizeFilter:
    def test_divisibility_filter_parks_tail(self):
        layers = lambda: host_layers(
            4, host_min=1, root_flag=LayerFlag.NONE, host_flag=LayerFlag.NONE
        )
        steps = simulate(
            8, layers, [(), (7,)], world_size_filter=lambda n: (n // 4) * 4
        )
        snap = steps[1]
        assert active_map(snap) == {0: 0, 1: 1, 2: 2, 3: 3}
        for r in (4, 5, 6):
            assert snap[r].mode is Mode.INACTIVE

    def test_filter_may_not_grow_world(self):
        t = Tree(host_layers(4), world_size_filter=lambda n: n + 1)
        with pytest.raises(RestartAbort):
            t(RankAssignmentCtx(State(rank=0, world_size=8), set()))


class TestTreeContract:
    def test_terminated_rank_discontinued(self):
        t = Tree(host_layers(4, host_min=1))
        with pytest.raises(RankDiscontinued):
            t(RankAssignmentCtx(State(rank=2, world_size=8), {2}))

    def test_mixed_root_keys_rejected(self):
        layers = [Layer(key_of_rank=lambda r: r % 2)]
        t = Tree(layers)
        with pytest.raises(RestartAbort):
            t(RankAssignmentCtx(State(rank=0, world_size=4), set()))

    def test_requires_layers(self):
        with pytest.raises(ValueError):
            Tree([])

    def test_single_layer_tree(self):
        (snap,) = simulate(
            4, lambda: [Layer(min_ranks=2, max_ranks=3, flag=LayerFlag.RESERVE)], [()]
        )
        assert active_map(snap) == {0: 0, 1: 1, 2: 2}
        assert snap[3].mode is Mode.INACTIVE

    def test_single_layer_reserve_promotion(self):
        steps = simulate(
            4,
            lambda: [Layer(min_ranks=2, max_ranks=3, flag=LayerFlag.RESERVE)],
            [(), (1,)],
        )
        assert active_map(steps[1]) == {0: 0, 3: 1, 2: 2}

    def test_tpu_pod_layers_shapes(self):
        layers = tpu_pod_layers(chips_per_host=4, hosts_per_slice=2, min_slices=1)
        assert len(layers) == 3
        assert layers[1].min_ranks == 8 and layers[1].max_ranks == 8
        assert layers[2].min_ranks == 4 and layers[2].max_ranks == 4

    def test_incremental_matches_fresh_instance(self):
        # a fresh Tree given the whole ordered log must agree with one that
        # saw the same terminations step by step (prefix-pure replay)
        layers = lambda: host_layers(4, host_min=1, host_max=3)
        steps = simulate(8, layers, [(), (0,), (5,)])
        final_incremental = active_map(steps[2])
        assert final_incremental == {3: 0, 1: 1, 2: 2, 4: 3, 7: 4, 6: 5}
        fresh = simulate(8, layers, [(0, 5)])
        assert active_map(fresh[0]) == final_incremental

    def test_batching_independence_brute_force(self):
        # THE Tree correctness property: the assignment is a pure function
        # of the ordered termination log prefix — HOW a rank's store reads
        # batch the same events must not matter.  Random topologies, random
        # kill orders, random batchings of the same order must all agree.
        import random

        rng = random.Random(20260729)
        for trial in range(120):
            chips = rng.choice([2, 3, 4])
            hosts = rng.choice([2, 3, 4])
            world = chips * hosts
            flags = [
                rng.choice([LayerFlag.NONE, LayerFlag.RESERVE, LayerFlag.BACKFILL])
                for _ in range(2)
            ]
            max_active = rng.choice([None, world // 2, world - 1])
            host_min = rng.choice([1, chips])
            # filter timing is the known batching hazard: _apply_filter must
            # run per-event, not per-call — keep it in the randomized space
            ws_filter = rng.choice([None, lambda n, c=chips: (n // c) * c])
            layers_fn = lambda: [
                Layer(min_ranks=1, max_ranks=max_active, key_of_rank="root",
                      flag=flags[0]),
                Layer(min_ranks=host_min, max_ranks=chips,
                      key_of_rank=lambda r, c=chips: r // c, flag=flags[1]),
            ]
            kills = rng.sample(range(world), rng.randint(1, world - 1))

            def final_map(batches):
                steps = simulate(
                    world, layers_fn, [()] + batches, world_size_filter=ws_filter
                )
                return active_map(steps[-1])

            one_batch = final_map([tuple(kills)])
            one_by_one = final_map([(k,) for k in kills])
            cut = rng.randint(1, len(kills))
            split = final_map([tuple(kills[:cut]), tuple(kills[cut:])])
            ctx = f"trial {trial}: chips={chips} hosts={hosts} flags={flags} " \
                  f"max_active={max_active} host_min={host_min} kills={kills}"
            assert one_batch == one_by_one == split, ctx
