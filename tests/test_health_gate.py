"""Pre-rendezvous health gate + failure injector (component 2.7-4).

Reference analog: ``testing_utils/health_check_injector.py`` (env-driven
``NVRX_INJECT_GPU_FAILURE="cycle:infra_rank"``) + the pre-join
UnhealthyNodeException path in ``ft_rendezvous_barrier.py``.
"""

import pytest

from tpu_resiliency.fault_tolerance.config import FaultToleranceConfig
from tpu_resiliency.fault_tolerance.health_gate import (
    ENV_INJECT,
    pre_rendezvous_health_check,
)
from tpu_resiliency.fault_tolerance.rendezvous import UnhealthyNodeError


def _cfg(**kw):
    defaults = dict(
        enable_device_health_check=False,
        enable_storage_health_check=False,
    )
    defaults.update(kw)
    return FaultToleranceConfig(**defaults)


class TestInjector:
    def test_fires_at_cycle_and_later(self, monkeypatch):
        monkeypatch.setenv(ENV_INJECT, "2:node-a")
        pre_rendezvous_health_check(_cfg(), "node-a", current_cycle=1)
        for cycle in (2, 3, 7):  # a dead node stays dead
            with pytest.raises(UnhealthyNodeError):
                pre_rendezvous_health_check(_cfg(), "node-a",
                                            current_cycle=cycle)

    def test_matches_node_id_substring_only(self, monkeypatch):
        monkeypatch.setenv(ENV_INJECT, "0:host3")
        with pytest.raises(UnhealthyNodeError):
            pre_rendezvous_health_check(_cfg(), "tpu-host3-slice0")
        pre_rendezvous_health_check(_cfg(), "tpu-host4-slice0")  # no match

    def test_malformed_spec_is_ignored(self, monkeypatch):
        for spec in ("nonsense", "x:node", ""):
            monkeypatch.setenv(ENV_INJECT, spec)
            pre_rendezvous_health_check(_cfg(), "node")

    def test_unset_env_passes(self, monkeypatch):
        monkeypatch.delenv(ENV_INJECT, raising=False)
        pre_rendezvous_health_check(_cfg(), "node")


class TestStorageGate:
    def test_writable_path_passes(self, tmp_path, monkeypatch):
        monkeypatch.delenv(ENV_INJECT, raising=False)
        cfg = _cfg(
            enable_storage_health_check=True,
            storage_health_check_path=str(tmp_path / "ckpt"),
        )
        pre_rendezvous_health_check(cfg, "node")
        # the probe cleans up after itself
        assert list((tmp_path / "ckpt").iterdir()) == []

    def test_unwritable_path_fails_the_gate(self, tmp_path, monkeypatch):
        monkeypatch.delenv(ENV_INJECT, raising=False)
        # a FILE where a directory is needed: makedirs raises for any uid
        # (chmod tricks don't block root, which CI may run as)
        blocker = tmp_path / "blocked"
        blocker.write_text("not a directory")
        cfg = _cfg(
            enable_storage_health_check=True,
            storage_health_check_path=str(blocker),
        )
        with pytest.raises(UnhealthyNodeError, match="storage"):
            pre_rendezvous_health_check(cfg, "node")

    def test_storage_gate_off_by_default(self, monkeypatch):
        monkeypatch.delenv(ENV_INJECT, raising=False)
        cfg = _cfg(storage_health_check_path="/definitely/not/writable")
        pre_rendezvous_health_check(cfg, "node")  # disabled -> not probed
