"""Hierarchical aggregation (store/tree.py): topology, gather semantics,
and the O(fanout) rank-0 inbound guarantee at a simulated 64-rank job."""

import json
import threading

import pytest

from tpu_resiliency.store import StoreClient, TreeGatherTimeout, TreeTopology, tree_gather
from tpu_resiliency.store.tree import combine_int_max, combine_json_merge


class CountingStore:
    """StoreClient wrapper tallying payloads consumed via multi_get — the
    tree's only inbound-read path, so the tally IS the inbound count."""

    def __init__(self, inner):
        self._inner = inner
        self.inbound_payloads = 0

    def multi_get(self, keys):
        out = self._inner.multi_get(keys)
        self.inbound_payloads += sum(1 for v in out if v is not None)
        return out

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TestTopology:
    def test_heap_shape(self):
        t = TreeTopology(0, 64, fanout=4)
        assert t.parent is None
        assert t.children == [1, 2, 3, 4]
        t5 = TreeTopology(5, 64, fanout=4)
        assert t5.parent == 1
        assert t5.children == [21, 22, 23, 24]
        leaf = TreeTopology(63, 64, fanout=4)
        assert leaf.children == []
        assert leaf.parent == 15

    def test_every_rank_has_consistent_parent(self):
        for fanout in (2, 4, 16):
            for world in (1, 2, 5, 64, 100):
                for r in range(1, world):
                    t = TreeTopology(r, world, fanout=fanout)
                    assert r in TreeTopology(t.parent, world, fanout=fanout).children

    def test_depth_logarithmic(self):
        assert TreeTopology(0, 64, fanout=4).depth() == 0
        assert TreeTopology(63, 64, fanout=4).depth() == 3
        assert TreeTopology(63, 64, fanout=16).depth() == 2


def _run_tree_round(store_server, world, fanout, broadcast=False, payload_fn=None,
                    combine=combine_json_merge, timeout=30.0, **gather_kw):
    """Drive one tree round with `world` threads; returns (results, stores)."""
    results, stores, errors = {}, {}, []

    def run(rank):
        inner = StoreClient("127.0.0.1", store_server.port, timeout=timeout)
        c = CountingStore(inner)
        stores[rank] = c
        payload = (
            payload_fn(rank) if payload_fn
            else json.dumps({rank: f"p{rank}"}).encode()
        )
        try:
            results[rank] = tree_gather(
                c, rank, world, prefix="t/round/0", payload=payload,
                combine=combine, timeout=timeout, fanout=fanout,
                broadcast=broadcast, site="test", **gather_kw,
            )
        except Exception as exc:  # noqa: BLE001
            errors.append((rank, exc))
        finally:
            inner.close()

    threads = [threading.Thread(target=run, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors[:3]
    return results, stores


class TestTreeGather:
    def test_single_rank(self, store_server):
        results, _ = _run_tree_round(store_server, 1, 4)
        assert json.loads(results[0]) == {"0": "p0"}

    def test_gather_merges_all_ranks(self, store_server):
        world = 10
        results, _ = _run_tree_round(store_server, world, 3)
        merged = {int(k): v for k, v in json.loads(results[0]).items()}
        assert merged == {r: f"p{r}" for r in range(world)}
        for r in range(1, world):
            assert results[r] is None

    def test_broadcast_hands_result_to_every_rank(self, store_server):
        world = 9
        results, _ = _run_tree_round(store_server, world, 3, broadcast=True)
        expected = {str(r): f"p{r}" for r in range(world)}
        for r in range(world):
            assert json.loads(results[r]) == expected

    def test_int_max_combiner(self, store_server):
        world = 7
        results, _ = _run_tree_round(
            store_server, world, 2, broadcast=True,
            payload_fn=lambda r: str(r * 11).encode(),
            combine=combine_int_max,
        )
        assert all(int(results[r]) == 66 for r in range(world))

    def test_round_leaves_no_node_keys(self, store_server):
        _run_tree_round(store_server, 8, 4)
        c = StoreClient("127.0.0.1", store_server.port)
        assert c.list_keys("t/round/0/n/") == []
        c.close()

    def test_timeout_names_missing_subtree(self, store_server):
        c = StoreClient("127.0.0.1", store_server.port, timeout=5.0)
        with pytest.raises(TreeGatherTimeout) as ei:
            tree_gather(
                c, 0, 4, prefix="t/dead", payload=b"{}",
                combine=combine_json_merge, timeout=0.4, fanout=4,
            )
        # children 1..3 never published; all are named
        assert ei.value.missing_ranks == [1, 2, 3]
        c.close()

    def test_rank0_inbound_is_fanout_at_64_ranks(self, store_server):
        """The acceptance gate: at a simulated 64-rank job the root consumes
        O(fanout) inbound payloads per round — NOT the flat gather's 63."""
        world, fanout = 64, 4
        results, stores = _run_tree_round(store_server, world, fanout)
        merged = {int(k): v for k, v in json.loads(results[0]).items()}
        assert len(merged) == world
        assert stores[0].inbound_payloads == fanout       # O(fanout), not O(N)
        for rank, c in stores.items():
            topo = TreeTopology(rank, world, fanout=fanout)
            assert c.inbound_payloads == len(topo.children) <= fanout


class TestPayloadCap:
    """Size-bounded partial aggregation (ROADMAP 2b): per-rank maps that
    grow O(world) toward the root are stride-sampled down to the cap at
    every tree level, with a ``_trimmed`` marker carrying the dropped
    population so the root knows what it is NOT seeing."""

    def test_payload_histogram_observes_combined_size(self, store_server):
        from tpu_resiliency.telemetry import get_registry

        reg = get_registry()
        before = reg.value_of("tpurx_tree_payload_bytes", {"site": "test"})
        _run_tree_round(store_server, 4, 2)
        after = reg.value_of("tpurx_tree_payload_bytes", {"site": "test"})
        assert after > before  # value_of yields the histogram sum

    def test_trim_unit_keeps_marker_accounting_across_levels(self):
        from tpu_resiliency.store.tree import trim_json_sampled

        obj = {str(i): "x" * 32 for i in range(100)}
        t1 = json.loads(trim_json_sampled(json.dumps(obj).encode(), 400))
        assert t1["_trimmed"]["total"] == 100
        kept1 = t1["_trimmed"]["kept"]
        assert kept1 == len(t1) - 1 < 100
        # re-trim at a higher level: survivors shrink again, but the true
        # population survives the marker hand-off
        t2 = json.loads(trim_json_sampled(json.dumps(t1).encode(), 150))
        assert t2["_trimmed"]["total"] == 100
        assert t2["_trimmed"]["kept"] == len(t2) - 1 <= kept1

    def test_gather_trims_over_cap(self, store_server):
        from tpu_resiliency.store.tree import trim_json_sampled

        world, fanout = 16, 4
        payload_fn = lambda r: json.dumps({str(r): "v" * 64}).encode()  # noqa: E731
        full, _ = _run_tree_round(store_server, world, fanout,
                                  payload_fn=payload_fn)
        # cap above any internal node's combine but below the root's: only
        # the root trims, so the marker accounting is exact
        cap = len(full[0]) * 2 // 3
        capped, _ = _run_tree_round(
            store_server, world, fanout, payload_fn=payload_fn,
            cap_bytes=cap, trim=trim_json_sampled,
        )
        merged = json.loads(capped[0])
        assert len(capped[0]) < len(full[0])
        marker = merged["_trimmed"]
        assert marker["total"] == world
        assert marker["kept"] == len(merged) - 1 < world

    def test_aggregator_skips_trim_marker(self, store_server, monkeypatch):
        """CrossRankAggregator opts into trimming: with a byte cap armed via
        the env knob, the round still aggregates (the ``_trimmed`` marker is
        bookkeeping, not a rank) and the observer feed filters it too."""
        from tpu_resiliency.telemetry.aggregate import (
            CrossRankAggregator, read_latest_snapshots,
        )
        from tpu_resiliency.telemetry.registry import Registry

        monkeypatch.setenv("TPURX_TREE_PAYLOAD_CAP", "700")
        world, fanout = 8, 4
        results, errors = {}, []

        def run(rank):
            reg = Registry(enabled=True)
            reg.counter("tpurx_cap8_total").inc(rank)
            inner = StoreClient("127.0.0.1", store_server.port, timeout=30.0)
            try:
                aggr = CrossRankAggregator(inner, rank, world, fanout=fanout)
                results[rank] = aggr.round(reg, timeout=30.0)
            except Exception as exc:  # noqa: BLE001
                errors.append((rank, exc))
            finally:
                inner.close()

        threads = [threading.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors[:3]
        assert results[0] is not None  # int(rank) never saw "_trimmed"
        c = StoreClient("127.0.0.1", store_server.port)
        latest = read_latest_snapshots(c)
        c.close()
        assert latest  # trimmed, but a representative subset survives
        assert set(latest) < set(range(world)) or set(latest) == set(range(world))
        assert all(isinstance(r, int) for r in latest)


class TestRoundsRouteThroughTree:
    """Telemetry snapshot gather + straggler report rounds + replication
    validity rounds all run through the reduction tree with O(fanout)
    rank-0 inbound, at a simulated 64-rank job."""

    def test_telemetry_aggregator_64_ranks(self, store_server):
        from tpu_resiliency.telemetry.aggregate import CrossRankAggregator
        from tpu_resiliency.telemetry.registry import Registry

        world, fanout = 64, 4
        results, stores, errors = {}, {}, []

        def run(rank):
            reg = Registry(enabled=True)
            reg.counter("tpurx_t64_total").inc(rank)
            inner = StoreClient("127.0.0.1", store_server.port, timeout=30.0)
            c = CountingStore(inner)
            stores[rank] = c
            try:
                aggr = CrossRankAggregator(c, rank, world, fanout=fanout)
                results[rank] = aggr.round(reg, timeout=30.0)
            except Exception as exc:  # noqa: BLE001
                errors.append((rank, exc))
            finally:
                inner.close()

        threads = [threading.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=90)
        assert not errors, errors[:3]
        agg = results[0]
        drops = agg["tpurx_t64_total"]["samples"][json.dumps({})]
        assert drops["sum"] == sum(range(world))
        assert drops["max_rank"] == world - 1
        assert stores[0].inbound_payloads == fanout
        # observers read the republished single-key feed
        from tpu_resiliency.telemetry.aggregate import read_latest_snapshots

        c = StoreClient("127.0.0.1", store_server.port)
        latest = read_latest_snapshots(c)
        assert set(latest) == set(range(world))
        c.close()

    def test_straggler_report_64_ranks(self, store_server, monkeypatch):
        from tpu_resiliency.straggler.detector import Detector

        monkeypatch.setenv("TPURX_TREE_FANOUT", "4")
        world = 64
        reports, stores, errors = {}, {}, []

        def run(rank):
            inner = StoreClient("127.0.0.1", store_server.port, timeout=30.0)
            c = CountingStore(inner)
            stores[rank] = c
            det = Detector(
                store=c, rank=rank, world_size=world, always_on=False,
            )
            det.initialize()
            with det.detection_section("step"):
                pass
            try:
                reports[rank] = det.generate_report(timeout=60.0)
            except Exception as exc:  # noqa: BLE001
                errors.append((rank, exc))
            finally:
                det.shutdown()
                inner.close()

        threads = [threading.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors[:3]
        assert set(reports[0].section_stats) == set(range(world))
        assert reports[1] is None  # gather_on_rank0 default
        assert stores[0].inbound_payloads == 4

    def test_replication_validity_round_uses_tree(self, tmp_path, store_server,
                                                  monkeypatch):
        """The manager's coverage/validity rounds route through tree_gather
        (spied), return correct coverage, and rank-0 inbound stays bounded
        by the fanout."""
        import tpu_resiliency.checkpointing.local.manager as manager_mod
        from tpu_resiliency.checkpointing.local.manager import (
            LocalCheckpointManager,
        )

        monkeypatch.setenv("TPURX_TREE_FANOUT", "4")
        calls = []
        real = manager_mod.tree_gather

        def spy(*args, **kwargs):
            calls.append(kwargs.get("site"))
            return real(*args, **kwargs)

        monkeypatch.setattr(manager_mod, "tree_gather", spy)
        world = 8
        found, errors = {}, []

        def run(rank):
            import numpy as np

            inner = StoreClient("127.0.0.1", store_server.port, timeout=30.0)
            try:
                mgr = LocalCheckpointManager(
                    root_dir=str(tmp_path / f"r{rank}"),
                    rank=rank,
                    world_size=world,
                    store=inner,
                )
                mgr.save({"w": np.full(4, rank, np.float32)}, iteration=3)
                mgr.wait()
                found[rank] = mgr.find_latest()
            except Exception as exc:  # noqa: BLE001
                errors.append((rank, exc))
            finally:
                inner.close()

        threads = [threading.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors[:3]
        assert all(found[r] == 3 for r in range(world))
        assert calls.count("ckpt_coverage") == world
