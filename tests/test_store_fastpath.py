"""One-RTT atomic store ops (APPEND_CHECK / ADD_SET / WAIT_GE): semantics on
both server implementations, Python<->C++ op-table parity, and the op-count
proof that barrier arrival and rendezvous registration are each a single
mutation round trip."""

import dataclasses
import json
import threading
import time
from pathlib import Path

import pytest

from tpu_resiliency.store import StoreClient, StoreServer, reentrant_barrier
from tpu_resiliency.store.client import StoreTimeout
from tpu_resiliency.store.protocol import (
    ADD_SLOT,
    CPP_OP_TABLE_BEGIN,
    CPP_OP_TABLE_END,
    Op,
    render_cpp_op_enum,
)

_REPO = Path(__file__).resolve().parents[1]

# every op that mutates the keyspace (reads, waits, and checks are free to
# repeat; mutations are what the 1-RTT claim counts)
_MUTATIONS = {
    Op.SET, Op.ADD, Op.APPEND, Op.COMPARE_SET, Op.DELETE, Op.MULTI_SET,
    Op.APPEND_CHECK, Op.ADD_SET,
}


class CountingStoreClient(StoreClient):
    """Records every opcode sent — the instrument behind the 1-RTT asserts."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.ops = []

    def _roundtrip(self, op, args, io_timeout, **kwargs):
        self.ops.append(Op(op))
        return super()._roundtrip(op, args, io_timeout, **kwargs)

    def mutations(self):
        return [op for op in self.ops if op in _MUTATIONS]


@pytest.fixture(params=["py", "native"])
def fast_store(request):
    """The new ops against BOTH servers: one protocol, two implementations."""
    if request.param == "py":
        server = StoreServer(host="127.0.0.1", port=0).start_in_thread()
    else:
        from tpu_resiliency.store.native import NativeStoreServer

        server = NativeStoreServer(host="127.0.0.1", port=0).start()
    client = StoreClient("127.0.0.1", server.port, timeout=10.0)
    yield client
    client.close()
    server.stop()


# -- op semantics, both servers ----------------------------------------------


class TestAppendCheck:
    def test_distinct_token_count_completes(self, fast_store):
        c = fast_store
        for i, expect_done in ((0, False), (1, False), (2, True)):
            new_len, done = c.append_check(
                "ac/arrivals", f"{i},", "ac/done", b"ok", required=3
            )
            assert done is expect_done
        assert c.get("ac/done") == b"ok"
        assert c.get("ac/arrivals") == b"0,1,2,"

    def test_reentry_deduplicates(self, fast_store):
        c = fast_store
        _, done = c.append_check("re/a", "0,", "re/done", b"1", required=2)
        assert not done
        # the same rank re-entering must not count twice
        _, done = c.append_check("re/a", "0,", "re/done", b"1", required=2)
        assert not done
        assert c.try_get("re/done") is None
        _, done = c.append_check("re/a", "1,", "re/done", b"1", required=2)
        assert done

    def test_explicit_tokens_ignore_outsiders(self, fast_store):
        c = fast_store
        toks = ["3", "5"]
        _, done = c.append_check("tk/a", "9,", "tk/done", b"1", tokens=toks)
        assert not done  # rank 9 is outside the narrowed set
        _, done = c.append_check("tk/a", "3,", "tk/done", b"1", tokens=toks)
        assert not done
        _, done = c.append_check("tk/a", "5,", "tk/done", b"1", tokens=toks)
        assert done

    def test_returns_new_length(self, fast_store):
        new_len, _ = fast_store.append_check("ln/a", "12,", "ln/d", b"1",
                                             required=9)
        assert new_len == 3
        new_len, _ = fast_store.append_check("ln/a", "7,", "ln/d", b"1",
                                             required=9)
        assert new_len == 5


class TestAddSet:
    def test_counter_spliced_into_record(self, fast_store):
        c = fast_store
        n = c.add_set("as/count", 1, "as/node/a",
                      b'{"arrival": ' + ADD_SLOT + b"}")
        assert n == 1
        assert json.loads(c.get("as/node/a")) == {"arrival": 1}
        n = c.add_set("as/count", 1, "as/node/b",
                      b'{"arrival": ' + ADD_SLOT + b"}")
        assert n == 2
        assert json.loads(c.get("as/node/b")) == {"arrival": 2}

    def test_value_without_slot_set_verbatim(self, fast_store):
        c = fast_store
        assert c.add_set("nv/count", 5, "nv/k", b"plain") == 5
        assert c.get("nv/k") == b"plain"

    def test_only_first_slot_spliced(self, fast_store):
        c = fast_store
        c.add_set("fs/count", 1, "fs/k", ADD_SLOT + b"|" + ADD_SLOT)
        assert c.get("fs/k") == b"1|" + ADD_SLOT


class TestWaitGe:
    def test_immediate_when_satisfied(self, fast_store):
        fast_store.set("ge/k", b"7")
        assert fast_store.wait_ge("ge/k", 5, timeout=5.0) == 7

    def test_missing_key_counts_as_zero(self, fast_store):
        assert fast_store.wait_ge("ge/missing", 0, timeout=5.0) == 0
        with pytest.raises(StoreTimeout):
            fast_store.wait_ge("ge/missing", 1, timeout=0.3)

    def test_blocks_until_threshold(self, fast_store):
        port = fast_store.port

        def bump():
            c = StoreClient("127.0.0.1", port)
            for _ in range(3):
                time.sleep(0.05)
                c.add("ge/ctr", 1)
            c.close()

        t = threading.Thread(target=bump)
        t.start()
        assert fast_store.wait_ge("ge/ctr", 3, timeout=10.0) >= 3
        t.join()

    def test_woken_by_add_set(self, fast_store):
        port = fast_store.port

        def join():
            c = StoreClient("127.0.0.1", port)
            time.sleep(0.1)
            c.add_set("ws/count", 1, "ws/node/x", b"desc")
            c.close()

        t = threading.Thread(target=join)
        t.start()
        assert fast_store.wait_ge("ws/count", 1, timeout=10.0) == 1
        # the record is readable the instant the counter moves
        assert fast_store.get("ws/node/x") == b"desc"
        t.join()

    def test_below_threshold_stays_parked(self, fast_store):
        fast_store.set("bt/k", b"1")
        port = fast_store.port

        def nudge():
            c = StoreClient("127.0.0.1", port)
            time.sleep(0.05)
            c.set("bt/k", b"2")  # wakes waiters, but still < 5
            c.close()

        t = threading.Thread(target=nudge)
        t.start()
        with pytest.raises(StoreTimeout):
            fast_store.wait_ge("bt/k", 5, timeout=0.6)
        t.join()


# -- Python <-> C++ op-table parity ------------------------------------------


class TestOpTableParity:
    def test_generated_block_is_verbatim_in_cpp_source(self):
        """The C++ enum is GENERATED from the Python Op table; the source
        must contain the current rendering byte-for-byte, so adding an op in
        one place and not the other fails here, not at runtime."""
        src = (_REPO / "native" / "store_server.cpp").read_text()
        block = render_cpp_op_enum()
        assert block in src, (
            "native/store_server.cpp op table is stale — regenerate with "
            "'python -m tpu_resiliency.store.protocol --cpp'"
        )
        # exactly one generated block
        assert src.count(CPP_OP_TABLE_BEGIN) == 1
        assert src.count(CPP_OP_TABLE_END) == 1

    def test_cpp_guard_uses_sentinel(self):
        """The unknown-op guard must reject via OP__LAST (which the
        generator maintains), not a hand-written literal that rots."""
        src = (_REPO / "native" / "store_server.cpp").read_text()
        assert "op > OP__LAST" in src

    def test_sentinel_tracks_highest_op(self):
        assert f"OP__LAST = {max(int(op) for op in Op)}," in render_cpp_op_enum()

    def test_every_python_op_in_rendering(self):
        block = render_cpp_op_enum()
        for op in Op:
            assert f"OP_{op.name} = {int(op)}," in block


# -- the 1-RTT claim, asserted by op count -----------------------------------


class TestOneRoundTripProtocols:
    def test_barrier_arrival_is_one_mutation(self, store_server):
        """Every reentrant-barrier participant — including the completer —
        issues exactly ONE mutation round trip (APPEND_CHECK).  The legacy
        path cost the completer three (APPEND, then read, then SET done)."""
        world = 3
        clients = [
            CountingStoreClient("127.0.0.1", store_server.port, timeout=10.0)
            for _ in range(world)
        ]
        threads = [
            threading.Thread(
                target=reentrant_barrier, args=(c, "rtt", i, world),
                kwargs={"timeout": 15.0},
            )
            for i, c in enumerate(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        for c in clients:
            assert c.mutations() == [Op.APPEND_CHECK], c.ops
            c.close()

    def test_rendezvous_join_is_one_mutation(self, store_server):
        """Joiner registration is ONE mutation round trip (ADD_SET carrying
        both the counter bump and the node record).  The legacy path cost
        three (ADD, SET node, SET count-marker)."""
        from tpu_resiliency.fault_tolerance.rendezvous import (
            NodeDesc,
            RendezvousHost,
            RendezvousJoiner,
        )

        host_client = StoreClient("127.0.0.1", store_server.port, timeout=30.0)
        host = RendezvousHost(
            host_client, min_nodes=2, max_nodes=2, settle_time=0.1
        )
        host.bootstrap()
        host.open_round()
        closer = threading.Thread(
            target=lambda: host.close_round_when_ready(timeout=30.0)
        )
        closer.start()
        clients = [
            CountingStoreClient("127.0.0.1", store_server.port, timeout=30.0)
            for _ in range(2)
        ]
        results = {}

        def join(i):
            results[i] = RendezvousJoiner(
                clients[i], NodeDesc.create(node_id=f"rtt-{i}", slots=1),
                open_poll_interval=0.05,
            ).join(timeout=30.0)

        joiners = [threading.Thread(target=join, args=(i,)) for i in range(2)]
        for t in joiners:
            t.start()
        for t in joiners:
            t.join(timeout=30)
        closer.join(timeout=30)
        assert len(results) == 2
        for c in clients:
            assert c.mutations() == [Op.ADD_SET], c.ops
            c.close()
        host_client.close()


# -- the arrival-slot splice --------------------------------------------------


class TestDescJsonArrivalSlot:
    def test_slot_splices_to_valid_json(self):
        from tpu_resiliency.fault_tolerance.rendezvous import (
            NodeDesc,
            _desc_json_with_arrival_slot,
        )

        desc = NodeDesc.create(node_id="n0", slots=4)
        raw = _desc_json_with_arrival_slot(desc)
        assert raw.count(ADD_SLOT) == 1
        spliced = raw.replace(ADD_SLOT, b"42", 1)
        got = NodeDesc.from_json(spliced)
        assert got.arrival == 42
        assert got.node_id == desc.node_id and got.slots == desc.slots

    def test_hostile_field_cannot_forge_slot(self):
        """A node_id that CONTAINS the arrival-field text must not divert
        the splice: JSON string escaping means the raw byte sequence
        '"arrival": 0' cannot occur inside a string value."""
        from tpu_resiliency.fault_tolerance.rendezvous import (
            NodeDesc,
            _desc_json_with_arrival_slot,
        )

        evil = dataclasses.replace(
            NodeDesc.create(node_id="x", slots=1),
            node_id='n"arrival": 0',
        )
        raw = _desc_json_with_arrival_slot(evil)
        assert raw.count(ADD_SLOT) == 1
        got = NodeDesc.from_json(raw.replace(ADD_SLOT, b"7", 1))
        assert got.arrival == 7
        assert got.node_id == 'n"arrival": 0'
