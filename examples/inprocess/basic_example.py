"""Minimal in-process restart (reference ``examples/inprocess/basic_example.py``).

The wrapped function restarts IN THE SAME PROCESS when any rank faults:
exceptions are recorded to the store, every rank's monitor thread trips,
async-raises ``RankShouldRestart`` into user code, ranks are reassigned
(``ShiftRanks``), and the function is called again with a fresh iteration.

Run N ranks against a store:

    python -m tpu_resiliency.store.server --host 127.0.0.1 --port 29450 &
    for r in 0 1; do
      TPURX_RANK=$r TPURX_WORLD_SIZE=2 \
      TPURX_STORE_ADDR=127.0.0.1 TPURX_STORE_PORT=29450 \
      python examples/inprocess/basic_example.py &
    done; wait
"""

import os
import sys
import time

sys.path.insert(0, os.environ.get("TPURX_REPO", "."))

from tpu_resiliency.inprocess import Wrapper  # noqa: E402


@Wrapper(
    soft_timeout=30.0,
    hard_timeout=60.0,
    # the monitor process needs a reachable store: TPURX_STORE_* env (set
    # above) or a StoreFactory
)
def train(call_wrapper=None):
    state = call_wrapper.state
    print(f"rank {state.active_rank}/{state.active_world_size} "
          f"iteration {call_wrapper.iteration}", flush=True)
    for step in range(20):
        call_wrapper.ping()  # progress signal for the hang monitors
        time.sleep(0.05)
        if (call_wrapper.iteration == 0 and state.active_rank == 1
                and step == 5):
            raise RuntimeError("injected fault: watch the in-process restart")
    return f"ok@{call_wrapper.iteration}"


if __name__ == "__main__":
    print("result:", train())
