"""Advanced in-process restart (reference ``examples/inprocess/advanced_example.py``).

Adds the production pieces to the basic example:

- ``Tree`` rank assignment: whole-host topology constraints with RESERVE
  spares — lose one chip and the whole host's ranks are replaced from the
  spare pool, keeping ICI domains intact.
- ``Compose`` plugins: initialize / abort / finalize hooks around each
  iteration (mesh rebuild, collective abort, state reload).
- The on-device **quorum tripwire**: pass the training mesh and a hang
  anywhere in the pod is detected by one ICI collective in milliseconds —
  the host soft/hard timeouts become the backstop, not the primary.

Single-process demo over an 8-device CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    TPURX_RANK=0 TPURX_WORLD_SIZE=1 \
    TPURX_STORE_ADDR=127.0.0.1 TPURX_STORE_PORT=29451 \
    python examples/inprocess/advanced_example.py   # (store on 29451)
"""

import os
import sys
import time

sys.path.insert(0, os.environ.get("TPURX_REPO", "."))

import jax  # noqa: E402

from tpu_resiliency.inprocess import (  # noqa: E402
    Compose,
    Layer,
    LayerFlag,
    ShiftRanks,
    Tree,
    Wrapper,
)
from tpu_resiliency.parallel.mesh import make_mesh  # noqa: E402


def log_iteration(frozen_state):
    print(f"[init] iteration={frozen_state.iteration} "
          f"rank={frozen_state.active_rank}", flush=True)
    return frozen_state


def rebuild_mesh(frozen_state):
    # rebuild meshes / reload state for the (possibly re-ranked) iteration
    return frozen_state


# plugins chain left-to-right: Compose(f, g)(state) == g(f(state))
on_initialize = Compose(log_iteration, rebuild_mesh)


def on_abort(frozen_state):
    # stop aux engines (checkpoint workers, exchanges) before the restart
    print("[abort] stopping aux engines", flush=True)


def assignment():
    chips_per_host = int(os.environ.get("CHIPS_PER_HOST", "4"))
    if int(os.environ.get("TPURX_WORLD_SIZE", "1")) >= 2 * chips_per_host:
        # pod topology: hosts of N chips; spare hosts park as RESERVE
        return Tree([
            Layer(min_size=1, flags=LayerFlag.RESERVE),
            Layer(min_size=chips_per_host, max_size=chips_per_host,
                  key="TPURX_HOST"),
        ])
    return ShiftRanks()


mesh = make_mesh(("all",), (len(jax.devices()),))


@Wrapper(
    rank_assignment=assignment(),
    initialize=on_initialize,
    abort=on_abort,
    soft_timeout=60.0,
    hard_timeout=120.0,
    quorum_mesh=mesh,            # ms-scale on-device hang detection
    quorum_interval=0.02,
    quorum_min_budget_ms=5.0,
)
def train(call_wrapper=None):
    for step in range(20):
        call_wrapper.ping()      # feeds host watchdog AND quorum stamps
        time.sleep(0.02)
        if step == 10:
            with call_wrapper.disable_hang_protection():
                time.sleep(0.3)  # known-long phase (compile, first load)
    return "done"


if __name__ == "__main__":
    print("result:", train())
