"""Sections API: phase-scoped hang detection.

Reference analog: ``examples/fault_tolerance/train_ddp_sections_api.py`` —
instead of one heartbeat cadence, the workload marks its phases
(``start_section``/``end_section``) and the monitor applies PER-SECTION
timeouts (a data-loader stall and a checkpoint stall have very different
budgets) plus an out-of-section timeout between phases.

    python -m tpu_resiliency.fault_tolerance.launcher \
        --nnodes 1 --nproc-per-node 2 --host-store \
        --rdzv-endpoint 127.0.0.1:29400 \
        --ft-cfg examples/fault_tolerance/ft_cfg_sections.yaml -- \
        examples/fault_tolerance/sections_example.py
"""

import os
import sys
import time

sys.path.insert(0, os.environ.get("TPURX_REPO", "."))

from tpu_resiliency.fault_tolerance import RankMonitorClient  # noqa: E402


def main() -> None:
    client = RankMonitorClient()
    client.init_workload_monitoring()

    for step in range(30):
        client.start_section("data")
        time.sleep(0.01)           # input pipeline
        client.end_section("data")

        client.start_section("step")
        time.sleep(0.04)           # jitted train step
        client.end_section("step")

        if step and step % 10 == 0:
            client.start_section("checkpoint")
            time.sleep(0.1)        # async save dispatch
            client.end_section("checkpoint")

    # learn per-section timeouts from the observed durations
    client.calculate_and_set_section_timeouts()
    client.shutdown_workload_monitoring()
    print("sections example: done (per-section timeouts learned)")


if __name__ == "__main__":
    main()
