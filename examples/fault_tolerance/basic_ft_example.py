"""Heartbeats API: the minimal fault-tolerance integration.

Reference analog: ``examples/fault_tolerance/basic_ft_example.py`` +
``train_ddp_heartbeats_api.py`` — a training loop that (1) connects to its
rank monitor, (2) heartbeats every step, (3) lets the monitor LEARN timeouts
from observed cadence, and (4) persists them for the next cycle.

Run under the launcher (which starts the monitors and the store):

    python -m tpu_resiliency.fault_tolerance.launcher \
        --nnodes 1 --nproc-per-node 2 --host-store \
        --rdzv-endpoint 127.0.0.1:29400 -- \
        examples/fault_tolerance/basic_ft_example.py
"""

import os
import sys
import time

sys.path.insert(0, os.environ.get("TPURX_REPO", "."))

from tpu_resiliency.fault_tolerance import RankMonitorClient  # noqa: E402


def main() -> None:
    rank = int(os.environ.get("TPURX_RANK", "0"))
    client = RankMonitorClient()
    client.init_workload_monitoring()

    # "{}" in FT_STATE is replaced with the rank: each rank persists its own
    # learned timeouts (concurrent writes to one file would tear the JSON)
    state_path = os.environ.get(
        "FT_STATE", "/tmp/ft_state_{}.json"
    ).format(rank)
    if os.path.exists(state_path):
        import json

        client.load_state_dict(json.load(open(state_path)))

    for step in range(50):
        # ... your training step here ...
        time.sleep(0.05)
        client.send_heartbeat()
        if step == 20:
            # after enough observed heartbeats, derive timeouts from the
            # real cadence instead of static defaults (safety_factor x max)
            client.calculate_and_set_hb_timeouts()

    import json

    json.dump(client.state_dict(), open(state_path, "w"))
    client.shutdown_workload_monitoring()
    print(f"rank {rank}: done, learned timeouts persisted to {state_path}")


if __name__ == "__main__":
    main()
