"""Straggler detection (reference ``examples/straggler/example.py``).

Wrap your jitted callables once; the always-on collector times every
dispatch to completion off-thread into native shared-memory rings (<1%
hot-path cost), CPU phases are timed with ``detection_section``, and on a
report cadence every rank's stats are gathered through the store and scored
relative to the fastest peer.

    JAX_PLATFORMS=cpu python examples/straggler/example.py
"""

import os
import sys
import time

sys.path.insert(0, os.environ.get("TPURX_REPO", "."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from tpu_resiliency.straggler import Detector  # noqa: E402


def main() -> None:
    det = Detector(
        rank=0, world_size=1,
        report_interval=8,
        always_on=True,            # native ring collector (default)
        profile_interval_s=0.0,    # >0: duty-cycled per-op XLA captures
    )
    det.initialize()

    @jax.jit
    def train_step(x):
        return (x @ x).sum()

    x = jnp.ones((512, 512))
    jax.block_until_ready(train_step(x))
    fns = det.wrap_callables({"train_step": train_step})
    step = fns["train_step"]

    for i in range(16):
        with det.detection_section("data"):
            time.sleep(0.002)      # input pipeline
        out = step(x)
        report = det.maybe_report()
        if report is not None:
            scores = report.relative_section_scores()
            print(f"round {report.round_idx}: relative scores {scores}")
    jax.block_until_ready(out)

    det.collector.flush()
    stats = det.collector.stats()["train_step"]
    print(f"always-on collector: {stats.count} samples, "
          f"median {stats.median * 1e3:.2f} ms "
          f"(arena shm: {det.collector.arena.shm_name} — readable by the "
          "rank monitor post-mortem)")
    det.shutdown()


if __name__ == "__main__":
    main()
