"""End-to-end example: resilient JAX training under the elastic launcher.

Run (single host, 2 workers, store hosted by the launcher):

    python -m tpu_resiliency.fault_tolerance.launcher \
        --nnodes 1 --nproc-per-node 2 --rdzv-endpoint 127.0.0.1:29500 \
        --host-store --max-restarts 3 --log-dir /tmp/tpurx-logs \
        examples/train_with_launcher.py

What it demonstrates:
- heartbeats + learned timeouts via FaultToleranceCallback,
- async global checkpoints every 20 steps + resume after restart,
- straggler detection sections,
- progress file for the launcher's crash-loop guard.

Inject a fault to watch the ring work:  TPURX_FAULT=sigkill:5 (env) kills a
worker 5s in; the launcher re-rendezvouses and training resumes from the
last committed checkpoint.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # demo mode: some TPU sandboxes force-register their platform via
    # sitecustomize, overriding the env var — override it back
    jax.config.update("jax_platforms", "cpu")

from tpu_resiliency.checkpointing import AsyncCheckpointer, load_checkpoint
from tpu_resiliency.checkpointing.async_ckpt.writer import is_committed
from tpu_resiliency.fault_tolerance.progress_tracker import write_progress_iteration
from tpu_resiliency.integrations import (
    CallbackRunner,
    FaultToleranceCallback,
    StragglerDetectionCallback,
)
from tpu_resiliency.models.transformer import (
    TransformerConfig,
    init_opt_state,
    init_params,
    make_batch,
    make_train_step,
)
from tpu_resiliency.utils.inject_fault import maybe_inject_from_env


def latest_checkpoint(root):
    best = None
    for name in os.listdir(root) if os.path.isdir(root) else ():
        if name.startswith("step_") and is_committed(os.path.join(root, name)):
            step = int(name.split("_")[1])
            best = max(best or -1, step)
    return best


def main():
    rank = int(os.environ.get("TPURX_RANK", "0"))
    total_steps = int(os.environ.get("STEPS", "60"))
    ckpt_root = os.environ.get("CKPT_DIR", "/tmp/tpurx-example-ckpts")
    os.makedirs(ckpt_root, exist_ok=True)
    maybe_inject_from_env(rank)

    cfg = TransformerConfig(
        vocab=1024, d_model=128, n_heads=4, n_layers=2, d_ff=256, max_seq=64
    )
    params = init_params(cfg)
    opt = init_opt_state(params)
    batch = make_batch(cfg, 4, 64)
    step_fn = make_train_step(cfg)

    ckpt = AsyncCheckpointer()
    start = 0
    last = latest_checkpoint(ckpt_root)
    if last is not None:
        restored = load_checkpoint(
            os.path.join(ckpt_root, f"step_{last}"), {"params": params, "opt": opt}
        )
        params, opt = restored["params"], restored["opt"]
        start = last + 1
        print(f"[rank {rank}] resumed from step {last}", flush=True)

    runner = CallbackRunner(
        [FaultToleranceCallback(warmup_steps=5, update_interval=20),
         StragglerDetectionCallback()]
    )
    runner.on_train_start(step=start)
    for step in range(start, total_steps):
        runner.on_step_start(step=step)
        params, opt, loss = step_fn(params, opt, batch)
        if step % 20 == 0 and rank == 0:
            ckpt.async_save(
                {"params": params, "opt": opt},
                os.path.join(ckpt_root, f"step_{step}"),
                extra_metadata={"iteration": step},
            )
        ckpt.maybe_finalize()
        if rank == 0:
            write_progress_iteration(
                os.environ.get("PROGRESS_FILE", "/tmp/tpurx-example-progress"), step
            )
        runner.on_step_end(step=step)
    ckpt.finalize_all()
    runner.on_train_end()
    print(f"[rank {rank}] done: loss={float(loss):.4f}", flush=True)


if __name__ == "__main__":
    main()
