"""Node health checks (reference ``examples/utils/node_health_check_example.py``).

Run the deep TPU node checks by hand — the same checks the rank monitor's
periodic health loop runs (``monitor_health_check_interval``) and the
launcher consults before joining a rendezvous round: accelerator sysfs,
kernel-ring fault signatures (AER/MCE/ECC/link-flap/worker-OOM), NIC error
windows, node daemon, and storage reachability.

    python examples/utils/node_health_check_example.py
"""

import os
import sys

sys.path.insert(0, os.environ.get("TPURX_REPO", "."))

from tpu_resiliency.health import PASSIVE_CHECKS, build_passive_checks  # noqa: E402


def main() -> None:
    chain = build_passive_checks(",".join(PASSIVE_CHECKS))
    results = [check.run() for check in chain.checks]
    for result in results:
        mark = "OK " if result.healthy else "FAIL"
        print(f"[{mark}] {result.name}: {result.message}")
    if all(r.healthy for r in results):
        print("node is healthy — would pass the pre-rendezvous gate")
    else:
        print("node is UNHEALTHY — the launcher would exclude it and a "
              "hot spare would take its slot")


if __name__ == "__main__":
    main()
