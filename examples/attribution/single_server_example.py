"""Attribution service (reference ``examples/attribution/single_server_example.py``).

Start attrsvc, submit a failing cycle's log, and read the verdict — the
same HTTP surface the launcher's restart gate uses
(``attribution_service_mode=spawn`` runs all of this for you; this example
drives it by hand).  Verdicts come from the rule engine, optionally
escalated to an LLM backend (``TPURX_LLM_URL``/``TPURX_LLM_MODEL`` env).

    python examples/attribution/single_server_example.py
"""

import json
import os
import sys
import threading
import urllib.request

sys.path.insert(0, os.environ.get("TPURX_REPO", "."))

from tpu_resiliency.services.attrsvc import serve  # noqa: E402

FAILING_LOG = """\
[r0] step 1200 loss=2.031
[r3] step 1200 loss=2.029
[r3] jaxlib.xla_extension.XlaRuntimeError: RESOURCE_EXHAUSTED:
[r3] Out of memory while trying to allocate 9663676416 bytes in hbm
[r0] collective timed out waiting for rank 3
"""


def main() -> None:
    server = serve(host="127.0.0.1", port=0)
    port = server.server_port
    threading.Thread(target=server.serve_forever, daemon=True).start()

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/analyze",
        data=json.dumps({"text": FAILING_LOG}).encode(),
        headers={"Content-Type": "application/json"},
    )
    verdict = json.loads(urllib.request.urlopen(req, timeout=30).read())
    print(f"category:      {verdict['category']}")
    print(f"should_resume: {verdict['should_resume']}")
    print(f"confidence:    {verdict['confidence']}")
    print(f"culprits:      {verdict['culprit_ranks']}")
    print(f"summary:       {verdict['summary']}")

    stats = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/stats", timeout=10).read())
    print(f"server stats:  {stats}")
    server.shutdown()


if __name__ == "__main__":
    main()
