"""Async checkpointing (reference ``examples/checkpointing/async_ckpt.py``).

Save a sharded pytree WITHOUT stalling training: ``async_save`` snapshots
device state in one jitted copy, a stager thread drains it to shared memory,
a deprioritized (nice + ionice-idle) worker process writes shards to disk,
and ``maybe_finalize`` commits once every process's plan signature agrees.

    JAX_PLATFORMS=cpu python examples/checkpointing/async_ckpt.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.environ.get("TPURX_REPO", "."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tpu_resiliency.checkpointing import AsyncCheckpointer  # noqa: E402
from tpu_resiliency.checkpointing.async_ckpt.checkpointer import (  # noqa: E402
    load_checkpoint,
)


def main() -> None:
    key = jax.random.PRNGKey(0)
    state = {
        "params": {"w": jax.random.normal(key, (256, 256)),
                   "b": jnp.zeros((256,))},
        "opt": {"m": jnp.zeros((256, 256)), "v": jnp.zeros((256, 256))},
        "step": np.int64(0),
    }
    root = tempfile.mkdtemp(prefix="async-ckpt-example-")
    ckpt = AsyncCheckpointer()
    try:
        for step in range(30):
            # ... train: state = train_step(state, batch) ...
            if step % 10 == 0:
                ckpt.async_save(
                    state, os.path.join(root, f"step_{step}"),
                    extra_metadata={"iteration": step},
                )
            ckpt.maybe_finalize()   # zero-wait commit check, call every step
        ckpt.finalize_all()         # drain before the demo exits
    finally:
        ckpt.close()

    restored = load_checkpoint(os.path.join(root, "step_20"), template=state)
    assert np.allclose(np.asarray(restored["params"]["w"]),
                       np.asarray(state["params"]["w"]))
    print(f"async checkpoint roundtrip OK under {root}")


if __name__ == "__main__":
    main()
