"""Node-local checkpointing with clique replication (reference
``examples/checkpointing/local_ckpt.py``).

Each rank saves its state to NODE-LOCAL disk (fast, no shared filesystem)
and replicates the blob to clique buddies — over rank↔rank TCP here, or over
the ICI interconnect with ``IciReplication`` (``ppermute`` moves the bytes
chip-to-chip at save time; recovery always rides TCP, since a broken mesh is
exactly when you recover).  Lose a node and ``find_latest``/``load`` restore
its state from the buddy.

This demo runs 2 "ranks" as threads with a real store + real TCP exchange:

    python examples/checkpointing/local_ckpt.py
"""

import os
import shutil
import sys
import tempfile
import threading

sys.path.insert(0, os.environ.get("TPURX_REPO", "."))

import numpy as np  # noqa: E402

from tpu_resiliency.checkpointing.local.manager import (  # noqa: E402
    LocalCheckpointManager,
)
from tpu_resiliency.checkpointing.local.replication import (  # noqa: E402
    CliqueReplication,
    PeerExchange,
)
from tpu_resiliency.store import StoreClient, StoreServer  # noqa: E402


def main() -> None:
    world = 2
    server = StoreServer(host="127.0.0.1", port=0).start_in_thread()
    root = tempfile.mkdtemp(prefix="local-ckpt-example-")
    states = {r: {"w": np.full((8, 8), float(r)), "step": np.int64(7)}
              for r in range(world)}

    def rank_main(rank, iteration, lose_my_dir=False):
        store = StoreClient("127.0.0.1", server.port)
        exchange = PeerExchange(store, rank)
        repl = CliqueReplication(exchange, world, replication_factor=2)
        node_dir = os.path.join(root, f"node{rank}")
        if lose_my_dir:
            shutil.rmtree(node_dir, ignore_errors=True)  # "node died"
        mgr = LocalCheckpointManager(
            node_dir, rank, world, store=store, replication=repl,
        )
        if not lose_my_dir and iteration is not None:
            mgr.save(states[rank], iteration=iteration, is_async=False)
            out = None
        else:
            latest = mgr.find_latest()
            tree, it = mgr.load(template=states[rank], iteration=latest)
            out = (tree, it)
        exchange.close()
        store.close()
        return out

    # phase 1: both ranks save (replicas land on the buddy's disk too)
    threads = [threading.Thread(target=rank_main, args=(r, 7))
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # phase 2: rank 1's node dir is destroyed; both ranks recover
    results = {}

    def recover(rank):
        results[rank] = rank_main(rank, None, lose_my_dir=(rank == 1))

    threads = [threading.Thread(target=recover, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tree, it = results[1]
    assert it == 7 and float(tree["w"][0, 0]) == 1.0
    server.stop()
    shutil.rmtree(root, ignore_errors=True)
    print("local checkpoint: node loss recovered from clique buddy (iter 7)")


if __name__ == "__main__":
    main()
