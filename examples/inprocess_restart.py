"""In-process restart example: recover from faults without losing the process.

Start a store, then N ranks (in separate shells or a loop):

    python -m tpu_resiliency.store.server --port 29500 &
    for r in 0 1 2; do
        TPURX_RANK=$r TPURX_WORLD_SIZE=3 \
        TPURX_STORE_ADDR=127.0.0.1 TPURX_STORE_PORT=29500 \
        python examples/inprocess_restart.py &
    done

Kill any rank (kill -9 <pid>): survivors detect it via the sibling/monitor
ring, re-assign ranks with ShiftRanks, and re-enter `train` with a smaller
world — same Python process, no scheduler round trip.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # demo mode: some TPU sandboxes force-register their platform via
    # sitecustomize, overriding the env var — override it back
    import jax

    jax.config.update("jax_platforms", "cpu")

from tpu_resiliency.inprocess import (
    AbortLadder,
    Compose,
    DeviceProbeHealthCheck,
    FaultCounter,
    ShiftRanks,
    ShrinkMeshStage,
    Wrapper,
)
from tpu_resiliency.inprocess.abort import ClearJaxCaches


@Wrapper(
    rank_assignment=ShiftRanks(),
    health_check=Compose(FaultCounter(max_faults=5), DeviceProbeHealthCheck(timeout=30)),
    # the staged abort ladder: the wrapper prepends its fingerprint rung
    # automatically; each rung runs with its own deadline and recorded
    # outcome (released / timed_out / escalate) — see docs/inprocess.md
    abort=AbortLadder(ShrinkMeshStage(), ClearJaxCaches()),
    soft_timeout=20.0,
    hard_timeout=40.0,
)
def train(call_wrapper=None):
    state = call_wrapper.state
    print(
        f"train: rank={state.active_rank}/{state.active_world_size} "
        f"iteration={call_wrapper.iteration}",
        flush=True,
    )
    for step in range(200):
        call_wrapper.ping()           # feed the hang watchdog
        time.sleep(0.1)               # "training step"
        if step % 50 == 0:
            print(f"rank {state.active_rank}: step {step}", flush=True)
    return "finished"


if __name__ == "__main__":
    print("pid:", os.getpid(), flush=True)
    print(train())
