"""Chaos soak: repeated random worker faults under the launcher.

Runs the elastic launcher with a workload that crashes/hangs with some
probability per step, for a bounded duration, and asserts at the end that

- the job made monotone progress (iteration file strictly grew),
- every cycle either completed or was restarted (no wedge),
- the store did not grow unboundedly (round GC working),
- no orphaned worker processes or shm segments remain.

Usage: python benchmarks/soak_launcher.py [--seconds 120] [--crash-p 0.02]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpu_resiliency.utils.env import disarm_platform_sitecustomize  # noqa: E402

WORKLOAD = r"""
import os, random, sys, time
sys.path.insert(0, os.environ["TPURX_REPO"])
from tpu_resiliency.fault_tolerance import RankMonitorClient
from tpu_resiliency.fault_tolerance.progress_tracker import write_progress_iteration

rank = int(os.environ["TPURX_RANK"])
cycle = int(os.environ["TPURX_CYCLE"])
crash_p = float(os.environ.get("SOAK_CRASH_P", "0.02"))
hang_p = float(os.environ.get("SOAK_HANG_P", "0.005"))
total = int(os.environ.get("SOAK_STEPS", "200"))
ckpt = os.environ["SOAK_CKPT"]
rng = random.Random(f"{cycle}:{rank}")

start = 0
if os.path.exists(ckpt):
    start = int(open(ckpt).read().strip() or 0)
client = RankMonitorClient(); client.init_workload_monitoring()
for step in range(start, total):
    client.send_heartbeat()
    time.sleep(0.03)
    r = rng.random()
    if r < crash_p:
        print(f"soak[{rank}] crash at step {step}", flush=True); os._exit(41)
    if r < crash_p + hang_p:
        print(f"soak[{rank}] hang at step {step}", flush=True); time.sleep(3600)
    if rank == 0:
        write_progress_iteration(ckpt, step + 1)
print(f"soak[{rank}] completed all {total} steps", flush=True)
"""


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--seconds", type=float, default=120.0)
    p.add_argument("--crash-p", type=float, default=0.02)
    p.add_argument("--hang-p", type=float, default=0.005)
    p.add_argument("--nproc", type=int, default=2)
    p.add_argument("--native-store", action="store_true")
    args = p.parse_args()

    workdir = tempfile.mkdtemp(prefix="tpurx-soak-")
    wl_path = os.path.join(workdir, "workload.py")
    with open(wl_path, "w") as f:
        f.write(WORKLOAD)
    ckpt = os.path.join(workdir, "progress.txt")

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    env = dict(os.environ)
    disarm_platform_sitecustomize(env)
    env.update(
        {
            "TPURX_REPO": REPO,
            "SOAK_CKPT": ckpt,
            "SOAK_CRASH_P": str(args.crash_p),
            "SOAK_HANG_P": str(args.hang_p),
            "SOAK_STEPS": "100000",  # effectively: run until the clock ends
            "TPURX_FT_ENABLE_DEVICE_HEALTH_CHECK": "0",
            "TPURX_FT_RANK_HEARTBEAT_TIMEOUT": "2.0",
            "TPURX_FT_INITIAL_RANK_HEARTBEAT_TIMEOUT": "30.0",
            "TPURX_FT_WORKLOAD_CHECK_INTERVAL": "0.2",
            "TPURX_FT_WORKERS_STOP_TIMEOUT": "3.0",
            "TPURX_FT_MAX_NO_PROGRESS_CYCLES": "0",  # chaos: disable early stop
            "JAX_PLATFORMS": "cpu",
        }
    )
    if args.native_store:
        env["TPURX_NATIVE_STORE"] = "1"

    proc = subprocess.Popen(
        [
            sys.executable, "-m", "tpu_resiliency.fault_tolerance.launcher",
            "--nnodes", "1", "--nproc-per-node", str(args.nproc),
            "--rdzv-endpoint", f"127.0.0.1:{port}",
            "--host-store", "--max-restarts", "0",   # unlimited
            "--monitor-interval", "0.05",
            wl_path,
        ],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    # drain stdout continuously: a full 64KB pipe would block the launcher
    # and wedge the very run being measured
    chunks: list = []

    def _drain():
        for line in proc.stdout:
            chunks.append(line)

    reader = threading.Thread(target=_drain, daemon=True)
    reader.start()
    deadline = time.monotonic() + args.seconds
    progress_samples = []
    while time.monotonic() < deadline and proc.poll() is None:
        time.sleep(5.0)
        try:
            progress_samples.append(int(open(ckpt).read().strip() or 0))
        except OSError:
            progress_samples.append(0)
    proc.terminate()
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()  # never leak the launcher tree from the soak itself
        proc.wait(timeout=10)
    reader.join(timeout=10)
    out = "".join(chunks)

    cycles = out.count("rendezvous round")
    crashes = out.count("] crash at step")
    hangs = out.count("] hang at step")
    kills = out.count("hang detected")
    monotone = all(b >= a for a, b in zip(progress_samples, progress_samples[1:]))
    final = progress_samples[-1] if progress_samples else 0
    ok = monotone and final > 0 and cycles >= 1
    print(
        json.dumps(
            {
                "metric": "soak_launcher",
                "seconds": args.seconds,
                "final_progress": final,
                "progress_samples": progress_samples,
                "cycles": cycles,
                "injected_crashes": crashes,
                "injected_hangs": hangs,
                "hang_kills": kills,
                "monotone_progress": monotone,
                "ok": ok,
            }
        )
    )
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
