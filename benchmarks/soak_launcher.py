"""Chaos soak: the full resiliency stack under randomized fault injection.

One command, repeatable, bounded; the round's regression gate (VERDICT r4
'do this' #9).  Stack under test: elastic launcher + rank monitors +
rendezvous + KV store (in-launcher or external control plane, optionally
the native C++ server) + in-process Wrapper ring + on-device quorum
tripwire, with four randomized fault classes injected per worker step:

- ``exception`` — absorbed by the in-process ring (no respawn),
- ``quorum_stall`` — ping-less stall; the on-device quorum collective
  trips and the in-process ring restarts the iteration,
- ``collective_wedge`` — the wedged-collective injection: the rank
  dispatches a named collective every step (feeding the at-abort
  fingerprint tail) and then parks ping-less "inside" it; the quorum
  tripwire trips, every rank's abort LADDER runs, and the report asserts
  the ladder's recorded stage outcomes (fingerprint rung released) from
  the profiling stream,
- ``hang`` — GIL-released C sleep; the rank monitor's heartbeat timeout
  kills the worker (outer ring respawn),
- ``crash`` — hard exit (outer ring respawn).

With ``--chaos-store`` the KV store runs as an EXTERNAL control plane
with a journal, and a chaos thread SIGKILLs and restarts it at random
intervals mid-run — launchers and monitors must ride the outage out.
With ``--store-kill-mid-save`` the kills are TARGETED instead: rank 0
runs a periodic store-backed "save" (chunked marker writes through the
unified retry policy) and the chaos thread kills the store inside the
save window — the gate asserts every started save still completed.

With ``--corrupt-blob {bitflip,truncate}`` the soak switches to the
checkpoint-integrity campaign: every rank runs a real
``LocalCheckpointManager`` (sealed blobs, clique replication over TCP),
saves every few steps, and in cycle 0 rank 0 corrupts EVERY copy of the
newest committed iteration (``utils.inject_fault.corrupt_checkpoint``)
then hard-exits.  The restarted gang must ``load(fallback=True)`` its way
down the ladder: the gate asserts the corrupt blobs were detected AND
quarantined (``*.corrupt`` debris on disk,
``tpurx_ckpt_corrupt_detected_total`` > 0 in-process), the restored
iteration is strictly OLDER than the corrupted one on every rank, and the
fallback-depth gauge is nonzero.

With ``--peer-mem-kill`` the soak runs the warm-restore campaign instead:
the same ``LocalCheckpointManager`` gang saves every few steps, then at a
drill step every non-serving rank drops its shm-resident copy and reloads
the newest iteration while the serving rank — fault-armed via
``TPURX_FAULT=peer_mem_stall`` — silently drops the peer-memory chunk
requests it receives.  The gate asserts the stalled rung timed out and
fell through to each rank's OWN DISK blob (``tpurx_ckpt_restore_source``
disk bytes > 0, peer bytes == 0) with ``tpurx_ckpt_fallback_depth`` 0:
a stalled peer degrades the restore to a colder source, never to an
older iteration.

With ``--link-degrade`` the soak runs the self-healing-collectives
campaign: every rank loops a wrapped collective
(``parallel.collectives.device_max_reduce``) while rank 0's PRIMARY lane
is fault-armed to stall past its deadline (``TPURX_FAULT=coll_stall``).
The gate asserts the wrapper handled the bad link entirely in process —
deadline trip (``tpurx_collective_timeouts_total`` > 0), degrade ladder
walked (``tpurx_collective_degrades_total`` > 0 on the armed rank only),
every rank FINISHED, and the launcher ring recorded ZERO restart cycles.

With ``--ramp-degrade`` the soak runs the predict-and-evacuate campaign:
one rank's health and straggler scores ramp worse round by round while
rank 0 hosts a ``PolicyController`` over the tree-gathered snapshot feed
with ``TPURX_EVAC=1``.  The gate asserts the fused rank risk evacuated
the ramping victim (checkpoint-ahead + published ``evac/`` record)
BEFORE its hard-fault deadline, that no healthy rank was ever evacuated,
and that the evacuated slot warm-joined chunk-granular from peer
holders' resident copies — peer-memory bytes > 0, disk bytes == 0, no
global restore round.

With ``--store-longpoll-abort`` the soak runs the interruptible-long-poll
campaign: each restart episode parks one rank deep in a server-held store
``wait()`` and a sibling injects a fault while it is parked.  The gate
asserts every injected abort LANDED on the parked rank (the async raise
arrives between poll-quantum I/O slices — the historical flake was a
~30s uninterruptible C-level recv swallowing it) within the
abort-propagation budget plus 2x ``TPURX_STORE_POLL_S``, and that no rank
ever exits ``ret=None``.

Every process appends profiling events to one JSONL
(``TPURX_PROFILING_FILE``); the report derives detect->recover latencies
for both rings from those events and ASSERTS bounds, so a regression in
any layer fails the gate rather than hiding in an average.

Gate (documented in README):    python benchmarks/soak_launcher.py --gate
Quick smoke (CI):               python benchmarks/soak_launcher.py --seconds 45
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpu_resiliency.utils.env import disarm_platform_sitecustomize  # noqa: E402

WORKLOAD = r"""
import os, random, sys, time
sys.path.insert(0, os.environ["TPURX_REPO"])
from tpu_resiliency.fault_tolerance import RankMonitorClient
from tpu_resiliency.fault_tolerance.progress_tracker import write_progress_iteration
from tpu_resiliency.inprocess import ShiftRanks, Wrapper, record_dispatch

rank = int(os.environ["TPURX_RANK"])
cycle = int(os.environ["TPURX_CYCLE"])
p_exc = float(os.environ.get("SOAK_EXC_P", "0.01"))
p_crash = float(os.environ.get("SOAK_CRASH_P", "0.008"))
p_hang = float(os.environ.get("SOAK_HANG_P", "0.004"))
p_qstall = float(os.environ.get("SOAK_QSTALL_P", "0.0"))
p_cwedge = float(os.environ.get("SOAK_CWEDGE_P", "0.0"))
save_every = int(os.environ.get("SOAK_SAVE_EVERY", "0"))
total = int(os.environ.get("SOAK_STEPS", "100000"))
ckpt = os.environ["SOAK_CKPT"]
rng = random.Random(f"{cycle}:{rank}:{os.getpid()}")

# seeded replay: a pre-drawn per-(rank, step) schedule replaces the RNG
# draws so two runs (e.g. the adaptive-vs-fixed A/B arms) see the EXACT
# same injection timeline
sched_path = os.environ.get("SOAK_FAULT_SCHEDULE", "")
fired_dir = os.environ.get("SOAK_FAULT_FIRED_DIR", "")
fault_sched = {}
if sched_path:
    import json as json_mod
    with open(sched_path) as f:
        fault_sched = json_mod.load(f)["faults"].get(str(rank), {})


def claim_fault(step):
    '''One-shot gate: restarts rewind the loop over already-run steps, so
    each scheduled injection fires exactly once via an O_EXCL marker.'''
    try:
        fd = os.open(os.path.join(fired_dir, f"r{rank}_s{step}"),
                     os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        os.close(fd)
        return True
    except OSError:
        return False

save_store = None
if save_every and rank == 0:
    from tpu_resiliency.store.client import store_from_env
    save_store = store_from_env(timeout=10.0)


def store_save(step):
    '''A store-backed "save": chunked marker writes, each riding the
    unified retry policy in the store client; the whole commit retried
    under the same policy — mid-save store kills must not lose a save.'''
    from tpu_resiliency.utils.retry import Retrier, RetryPolicy
    print(f"soak[{rank}] save start at step {step}", flush=True)
    r = Retrier("soak_save", RetryPolicy(max_attempts=None, base_delay=0.5,
                                         max_delay=3.0, deadline=60.0))
    while True:
        try:
            for i in range(8):
                save_store.set(f"soakckpt/{step}/{i}", str(step))
                time.sleep(0.08)
            save_store.set(f"soakckpt/{step}/commit", "1")
            break
        except Exception as exc:
            r.backoff(exc)
    print(f"soak[{rank}] save done at step {step}", flush=True)

quorum_kw = {}
if os.environ.get("SOAK_QUORUM") == "1":
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh
    quorum_kw = dict(
        quorum_mesh=Mesh(np.array(jax.devices()), ("d",)),
        quorum_budget_ms=float(os.environ.get("SOAK_QUORUM_BUDGET_MS", "500")),
        quorum_interval=0.05,
        quorum_auto_beat_interval=None,   # manual ping only: progress semantics
        quorum_calibrate=False,
    )

client = RankMonitorClient(); client.init_workload_monitoring()


@Wrapper(
    group=f"soak-c{cycle}",
    rank_assignment=ShiftRanks(),
    soft_timeout=3600.0, hard_timeout=7200.0,   # host ring owns hang kills
    monitor_thread_interval=0.1,
    heartbeat_interval=0.2, sibling_timeout=8.0,
    last_call_wait=0.1,
    enable_monitor_process=False,  # rank monitor (launcher ring) is the backstop here
    **quorum_kw,
)
def run(call_wrapper=None):
    start = 0
    if os.path.exists(ckpt):
        try:
            start = int(open(ckpt).read().strip() or 0)
        except ValueError:
            start = 0
    for step in range(start, total):
        call_wrapper.ping()
        client.send_heartbeat()
        record_dispatch("soak_allreduce")   # at-abort fingerprint feed
        time.sleep(0.03)
        if save_every and save_store is not None and step and step % save_every == 0:
            store_save(step)
        if sched_path:
            kind = fault_sched.get(str(step))
            if kind and claim_fault(step):
                print(f"soak[{rank}] {kind} at step {step}", flush=True)
                if kind == "crash":
                    os._exit(41)
                if kind == "hang":
                    time.sleep(3600)
                if kind in ("quorum stall", "collective wedge"):
                    while True:
                        time.sleep(0.02)
                raise RuntimeError(f"scheduled exception step {step}")
            if call_wrapper.state.active_rank == 0:
                write_progress_iteration(ckpt, step + 1)
            continue
        r = rng.random()
        if r < p_crash:
            print(f"soak[{rank}] crash at step {step}", flush=True); os._exit(41)
        r -= p_crash
        if r < p_hang:
            print(f"soak[{rank}] hang at step {step}", flush=True)
            time.sleep(3600)   # GIL released; heartbeat timeout must kill us
        r -= p_hang
        if r < p_exc:
            print(f"soak[{rank}] exception at step {step}", flush=True)
            raise RuntimeError(f"injected exception step {step}")
        r -= p_exc
        if r < p_qstall and quorum_kw:
            print(f"soak[{rank}] quorum stall at step {step}", flush=True)
            while True:     # ping-less python loop: quorum trips, raise lands
                time.sleep(0.02)
        r -= p_qstall
        if r < p_cwedge and quorum_kw:
            # wedged-collective injection: the collective was DISPATCHED
            # (it's in the tail) and this rank now parks "inside" it —
            # the ladder's fingerprint rung must name soak_allreduce
            print(f"soak[{rank}] collective wedge at step {step}", flush=True)
            while True:
                time.sleep(0.02)
        if call_wrapper.state.active_rank == 0:
            write_progress_iteration(ckpt, step + 1)
    return "done"

print(f"soak[{rank}] result={run()}", flush=True)
"""


WORKLOAD_LCKPT = r"""
import os, sys, time
sys.path.insert(0, os.environ["TPURX_REPO"])
import numpy as np
from tpu_resiliency.fault_tolerance import RankMonitorClient
from tpu_resiliency.store.client import store_from_env
from tpu_resiliency.checkpointing.local.manager import LocalCheckpointManager
from tpu_resiliency.checkpointing.local.replication import (
    CliqueReplication, PeerExchange)
from tpu_resiliency.telemetry import get_registry
from tpu_resiliency.utils.inject_fault import Fault, corrupt_checkpoint

rank = int(os.environ["TPURX_RANK"])
world = int(os.environ["TPURX_WORLD_SIZE"])
cycle = int(os.environ["TPURX_CYCLE"])
root = os.environ["SOAK_CKPT_ROOT"]
save_every = int(os.environ.get("SOAK_LCKPT_EVERY", "10"))
corrupt_step = int(os.environ.get("SOAK_CORRUPT_STEP", "35"))
drill_step = int(os.environ.get("SOAK_PEER_DRILL_STEP", "0"))
mode = os.environ.get("SOAK_CORRUPT_MODE", "bitflip")
total = int(os.environ.get("SOAK_STEPS", "100000"))


def metric_sum(name):
    m = get_registry().get(name)
    if m is None:
        return 0.0
    return sum(v.get("value", 0.0) for _l, v in m._sample_rows())


def source_bytes(src):
    return get_registry().value_of(
        "tpurx_ckpt_restore_source_total", {"source": src})


client = RankMonitorClient(); client.init_workload_monitoring()
store = store_from_env(timeout=15.0)
ex = PeerExchange(store, rank, namespace=f"soaklc-c{cycle}")
repl = CliqueReplication(ex, world, replication_factor=min(2, world))
mgr = LocalCheckpointManager(
    os.path.join(root, f"n{rank}"), rank, world, store=store,
    replication=repl, keep_last=8, peer_timeout=30.0,
    store_namespace=f"localckpt/c{cycle}",
)


def make_tree(step):
    return {"w": np.full((4096,), float(step), dtype=np.float32),
            "step": np.int64(step),
            "rank_marker": np.array([rank], dtype=np.int32)}


start = 0
if mgr.find_latest() is not None:
    tree, it = mgr.load(make_tree(0), fallback=True)
    depth = int(get_registry().get("tpurx_ckpt_fallback_depth").value)
    assert int(tree["step"]) == it, (int(tree["step"]), it)
    assert int(tree["rank_marker"][0]) == rank, "restored ANOTHER rank's data"
    import glob as glob_mod
    debris = len(glob_mod.glob(
        os.path.join(root, f"n{rank}", "**", "*.corrupt"), recursive=True))
    print(f"soaklc[{rank}] restored iter={it} depth={depth} "
          f"corrupt={int(metric_sum('tpurx_ckpt_corrupt_detected_total'))} "
          f"quarantined={int(metric_sum('tpurx_ckpt_quarantined_total'))} "
          f"debris={debris}",
          flush=True)
    start = it + 1
else:
    print(f"soaklc[{rank}] fresh start (no checkpoint)", flush=True)

for step in range(start, total):
    client.send_heartbeat()
    time.sleep(0.05)
    if step and step % save_every == 0:
        mgr.save(make_tree(step), iteration=step, is_async=False)
        print(f"soaklc[{rank}] saved iter={step}", flush=True)
    if drill_step and step == drill_step and mgr.find_latest() is not None:
        # peer-memory stall drill: the serving peer (TPURX_FAULT_RANKS)
        # silently drops chunk requests, so every other rank — having shed
        # its own resident copy — must try the peer-memory rung, time out,
        # and fall through to its own disk blob WITHOUT burning a fallback
        # rung (depth stays 0: same iteration, colder source)
        it = mgr.find_latest()
        peer0, disk0 = source_bytes("peer_memory"), source_bytes("local_disk")
        if rank != 0:
            mgr.drop_resident()
        t0 = time.time()
        tree2, it2 = mgr.load(make_tree(0), iteration=it)
        depth = int(get_registry().get("tpurx_ckpt_fallback_depth").value)
        assert int(tree2["rank_marker"][0]) == rank, "restored ANOTHER rank's data"
        print(f"soaklc[{rank}] peer-drill it={it2} "
              f"disk_b={int(source_bytes('local_disk') - disk0)} "
              f"peer_b={int(source_bytes('peer_memory') - peer0)} "
              f"depth={depth} s={time.time() - t0:.2f}", flush=True)
    if cycle == 0 and rank == 0 and step == corrupt_step:
        mutated = corrupt_checkpoint(root, Fault(mode))
        its = sorted({os.path.basename(os.path.dirname(p)) for p in mutated})
        print(f"soaklc[{rank}] corrupted newest mode={mode} "
              f"files={len(mutated)} iters={','.join(its)}", flush=True)
        time.sleep(0.3)
        os._exit(41)
print(f"soaklc[{rank}] result=done", flush=True)
"""


WORKLOAD_COLL = r"""
import os, sys, time
sys.path.insert(0, os.environ["TPURX_REPO"])
from tpu_resiliency.fault_tolerance import RankMonitorClient
from tpu_resiliency.fault_tolerance.progress_tracker import write_progress_iteration
from tpu_resiliency.parallel import device_max_reduce
from tpu_resiliency.telemetry import get_registry

rank = int(os.environ["TPURX_RANK"])
world = int(os.environ["TPURX_WORLD_SIZE"])
total = int(os.environ.get("SOAK_COLL_STEPS", "25"))
ckpt = os.environ["SOAK_CKPT"]


def metric_sum(name):
    m = get_registry().get(name)
    if m is None:
        return 0.0
    return sum(v.get("value", 0.0) for _l, v in m._sample_rows())


client = RankMonitorClient(); client.init_workload_monitoring()
from tpu_resiliency.store.client import store_from_env
store = store_from_env(timeout=10.0)
for step in range(total):
    client.send_heartbeat()
    # every step runs one wrapped collective; on the fault-armed rank
    # (TPURX_FAULT=coll_stall) the primary lane stalls past its deadline
    # and the wrapper must degrade (retry -> re-layout) IN PROCESS — the
    # launcher ring must never see a restart
    got = device_max_reduce([float(step)])
    assert got and got[0] >= float(step), (got, step)
    time.sleep(0.02)
    if rank == 0:
        write_progress_iteration(ckpt, step + 1)
# gang-synchronized exit: a rank exiting while the degraded rank is still
# grinding reads as a failure to the launcher ring, which would restart
# the gang and mask the zero-restart assertion
store.set(f"soakcoll/done/r{rank}", "1")
t_barrier = time.monotonic()
while time.monotonic() - t_barrier < 120.0:
    client.send_heartbeat()
    if all(store.try_get(f"soakcoll/done/r{r}") is not None
           for r in range(world)):
        break
    time.sleep(0.2)
print(f"soakcoll[{rank}] result=done "
      f"degrades={int(metric_sum('tpurx_collective_degrades_total'))} "
      f"timeouts={int(metric_sum('tpurx_collective_timeouts_total'))}",
      flush=True)
"""


WORKLOAD_EVAC = r"""
import json, os, sys, time
sys.path.insert(0, os.environ["TPURX_REPO"])
import numpy as np
from tpu_resiliency.fault_tolerance import RankMonitorClient
from tpu_resiliency.store.client import store_from_env
from tpu_resiliency.checkpointing.local.manager import LocalCheckpointManager
from tpu_resiliency.checkpointing.local.replication import (
    CliqueReplication, PeerExchange)
from tpu_resiliency.policy import (
    EvacuationPipeline, PolicyController, SnapshotFeed,
    set_evacuation_handler)
from tpu_resiliency.telemetry import get_registry
from tpu_resiliency.telemetry.aggregate import (
    CrossRankAggregator, read_latest_snapshots)

rank = int(os.environ["TPURX_RANK"])
world = int(os.environ["TPURX_WORLD_SIZE"])
cycle = int(os.environ["TPURX_CYCLE"])
victim = int(os.environ.get("SOAK_EVAC_VICTIM", "1"))
ramp_rounds = int(os.environ.get("SOAK_EVAC_RAMP_ROUNDS", "12"))
deadline_step = int(os.environ.get("SOAK_EVAC_DEADLINE", "45"))
root = os.environ["SOAK_CKPT_ROOT"]
save_every = int(os.environ.get("SOAK_LCKPT_EVERY", "5"))
total = int(os.environ.get("SOAK_STEPS", "200"))

client = RankMonitorClient(); client.init_workload_monitoring()
store = store_from_env(timeout=15.0)
ex = PeerExchange(store, rank, namespace=f"soakev-c{cycle}")
repl = CliqueReplication(ex, world, replication_factor=min(2, world))
mgr = LocalCheckpointManager(
    os.path.join(root, f"n{rank}"), rank, world, store=store,
    replication=repl, keep_last=8, peer_timeout=30.0,
    store_namespace=f"localckpt/c{cycle}",
)
agg = CrossRankAggregator(store, rank, world)
reg = get_registry()
health = reg.gauge("tpurx_health_score", labels=("check",))
strag = reg.gauge("tpurx_straggler_score", labels=("rank",))


def source_bytes(src):
    return get_registry().value_of(
        "tpurx_ckpt_restore_source_total", {"source": src})


def make_tree(step):
    return {"w": np.full((4096,), float(step), dtype=np.float32),
            "step": np.int64(step),
            "rank_marker": np.array([rank], dtype=np.int32)}


pipe = EvacuationPipeline(store=store, rank=rank,
                          shrink_fn=lambda victim_rank: None)
ctl = None
if rank == 0:
    # job-level controller over the tree-gathered snapshot feed; the
    # handler runs the real pipeline (checkpoint-ahead + record publish)
    # and announces a future JOIN step every rank will reach in lockstep
    step_box = {"step": 0}

    def on_evacuate(victim_rank, reason):
        join_step = step_box["step"] + 10
        pipe.evacuate(victim_rank, reason=reason)
        store.set(f"soakev/c{cycle}/evacuate", json.dumps(
            {"victim": victim_rank, "join_step": join_step}))
        print(f"soakev[0] EVACUATE rank={victim_rank} "
              f"at step={step_box['step']} join_step={join_step}",
              flush=True)

    set_evacuation_handler(on_evacuate)
    ctl = PolicyController(
        feed=SnapshotFeed(lambda: read_latest_snapshots(store)),
        store=store)

joined = False
for step in range(total):
    client.send_heartbeat()
    time.sleep(0.05)
    if step and step % save_every == 0 and not joined:
        mgr.save(make_tree(step), iteration=step, is_async=False)
    # the ramping degradation: the victim's node health worsens round by
    # round, and the straggler report scores it slower and slower —
    # nothing hard-faults until the deadline below
    if rank == victim:
        health.labels("soak_ramp").set(min(1.0, step / ramp_rounds))
    if rank == 0:
        for r in range(world):
            score = (max(0.2, 1.0 - step / ramp_rounds)
                     if r == victim else 1.0)
            strag.labels(str(r)).set(score)
    agg.round(reg, timeout=60.0)
    if ctl is not None:
        step_box["step"] = step
        ctl.tick()
    plan_raw = store.try_get(f"soakev/c{cycle}/evacuate")
    if plan_raw is not None and not joined:
        plan = json.loads(plan_raw.decode()
                          if isinstance(plan_raw, bytes) else plan_raw)
        if step >= int(plan["join_step"]):
            # the handoff: every rank joins the collective restore round;
            # the evacuated slot drops its resident copy first, so its
            # bytes must come CHUNK-GRANULAR off peer holders' memory —
            # never a disk rung, never a global restore
            it = mgr.find_latest()
            peer0 = source_bytes("peer_memory")
            disk0 = (source_bytes("local_disk")
                     + source_bytes("peer_disk"))
            if rank == int(plan["victim"]):
                mgr.drop_resident()
                out = pipe.warm_join(mgr, make_tree(0), iteration=it)
                peer_b = int(source_bytes("peer_memory") - peer0)
                disk_b = int(source_bytes("local_disk")
                             + source_bytes("peer_disk") - disk0)
                assert int(out["tree"]["rank_marker"][0]) == rank
                print(f"soakev[{rank}] JOIN warm={out['warm']} "
                      f"iter={out['iteration']} peer_b={peer_b} "
                      f"disk_b={disk_b} "
                      f"dur_ms={out['dur_ms']:.1f}", flush=True)
            else:
                mgr.load(make_tree(0), iteration=it)
            joined = True
            break  # every rank leaves at the SAME plan step
    if rank == victim and step >= deadline_step and not joined:
        print(f"soakev[{rank}] HARD FAULT at step {step}", flush=True)
        os._exit(41)
# gang-synchronized exit (a lone early exit reads as a failure to the
# launcher ring and would restart the gang)
store.set(f"soakev/c{cycle}/done/r{rank}", "1")
t_barrier = time.monotonic()
while time.monotonic() - t_barrier < 120.0:
    client.send_heartbeat()
    if all(store.try_get(f"soakev/c{cycle}/done/r{r}") is not None
           for r in range(world)):
        break
    time.sleep(0.2)
print(f"soakev[{rank}] result=done joined={joined}", flush=True)
"""


WORKLOAD_LONGPOLL = r"""
import os, sys, time
sys.path.insert(0, os.environ["TPURX_REPO"])
from tpu_resiliency.fault_tolerance import RankMonitorClient
from tpu_resiliency.inprocess import ShiftRanks, Wrapper
from tpu_resiliency.store.client import StoreTimeout, store_from_env

rank = int(os.environ["TPURX_RANK"])
cycle = int(os.environ["TPURX_CYCLE"])
inject_delay = float(os.environ.get("SOAK_LP_INJECT_DELAY", "2.0"))

client = RankMonitorClient(); client.init_workload_monitoring()
store = store_from_env(timeout=30.0)


@Wrapper(
    group=f"soaklp-c{cycle}",
    rank_assignment=ShiftRanks(),
    soft_timeout=3600.0, hard_timeout=7200.0,
    monitor_thread_interval=0.05,
    heartbeat_interval=0.1, sibling_timeout=5.0,
    last_call_wait=0.1,
    enable_monitor_process=False,
)
def run(call_wrapper=None):
    # One fault EPISODE per restart iteration: active rank 0 parks deep in a
    # server-held store long poll, active rank 1 raises after inject_delay.
    # The in-process ring's async abort must LAND on the parked rank between
    # poll-quantum slices (the historical flake: one ~30s C-level recv
    # swallowed the raise and the rank exited ret=None).  Both sides print
    # CLOCK_MONOTONIC stamps (system-wide on Linux) so the report can
    # compute injection->landing latency across processes.
    while True:
        call_wrapper.ping()
        client.send_heartbeat()
        ep = call_wrapper.state.iteration
        me = call_wrapper.state.active_rank
        if me == 0:
            print(f"soaklp[{rank}] park ep={ep} t={time.monotonic():.6f}",
                  flush=True)
            try:
                store.wait([f"soaklp/never/c{cycle}/ep{ep}"], timeout=120.0)
            except StoreTimeout:
                pass  # episode fizzled (injector restarted first); re-park
            except BaseException:
                print(f"soaklp[{rank}] landed ep={ep} "
                      f"t={time.monotonic():.6f}", flush=True)
                raise
        elif me == 1:
            # stay live for the heartbeat ring while the victim parks
            t0 = time.monotonic()
            while time.monotonic() - t0 < inject_delay:
                call_wrapper.ping()
                client.send_heartbeat()
                time.sleep(0.05)
            print(f"soaklp[{rank}] inject ep={ep} t={time.monotonic():.6f}",
                  flush=True)
            raise RuntimeError(f"soaklp scheduled abort ep={ep}")
        else:
            # spectator ranks idle-heartbeat until the episode's abort lands
            while True:
                call_wrapper.ping()
                client.send_heartbeat()
                time.sleep(0.05)

print(f"soaklp[{rank}] result={run()}", flush=True)
"""


WORKLOAD_GOODPUT = r"""
import json, os, sys, time
sys.path.insert(0, os.environ["TPURX_REPO"])
from tpu_resiliency.fault_tolerance import RankMonitorClient
from tpu_resiliency.fault_tolerance.progress_tracker import write_progress_iteration
from tpu_resiliency.inprocess import ShiftRanks, Wrapper, record_dispatch
from tpu_resiliency.checkpointing.async_ckpt.checkpointer import SaveScheduler
from tpu_resiliency.telemetry import get_registry

rank = int(os.environ["TPURX_RANK"])
cycle = int(os.environ["TPURX_CYCLE"])
ckpt = os.environ["SOAK_CKPT"]
step_s = float(os.environ.get("SOAK_STEP_S", "0.02"))
save_cost_s = float(os.environ.get("SOAK_SAVE_COST_S", "0.4"))
fixed_interval_s = float(os.environ.get("SOAK_SAVE_INTERVAL_S", "4.0"))
total = int(os.environ.get("SOAK_STEPS", "100000"))
with open(os.environ["SOAK_FAULT_SCHEDULE"]) as f:
    faults = json.load(f)["faults"].get(str(rank), {})
fired_dir = os.environ["SOAK_FAULT_FIRED_DIR"]


def claim_fault(step):
    try:
        fd = os.open(os.path.join(fired_dir, f"r{rank}_s{step}"),
                     os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        os.close(fd)
        return True
    except OSError:
        return False


client = RankMonitorClient(); client.init_workload_monitoring()

# the adaptive arm: a per-rank closed loop over this rank's own telemetry
# — the estimator measures MTBF / C / R from the SAME counters the real
# stack records (interruptions, save-call latency, restart latency) and
# retunes TPURX_CKPT_INTERVAL_S through the actuator; the fixed arm runs
# the identical workload with the policy off
policy_ctl = None
if os.environ.get("TPURX_POLICY", "0") == "1":
    from tpu_resiliency.policy import PolicyController
    policy_ctl = PolicyController()
    policy_ctl.start(
        interval_s=float(os.environ.get("TPURX_POLICY_INTERVAL_S", "2.0")))

scheduler = SaveScheduler(default_interval_s=fixed_interval_s)
SAVE_NS = get_registry().get("tpurx_ckpt_save_call_ns")


@Wrapper(
    group=f"goodput-c{cycle}",
    rank_assignment=ShiftRanks(),
    soft_timeout=3600.0, hard_timeout=7200.0,
    monitor_thread_interval=0.1,
    heartbeat_interval=0.2, sibling_timeout=8.0,
    last_call_wait=0.1,
    enable_monitor_process=False,
)
def run(call_wrapper=None):
    start = 0
    if os.path.exists(ckpt):
        try:
            start = int(open(ckpt).read().strip() or 0)
        except ValueError:
            start = 0
    for step in range(start, total):
        call_wrapper.ping()
        client.send_heartbeat()
        record_dispatch("goodput_allreduce")
        time.sleep(step_s)           # the useful work
        if scheduler.due():          # re-reads TPURX_CKPT_INTERVAL_S
            t0 = time.monotonic_ns()
            time.sleep(save_cost_s)  # the checkpoint cost C
            scheduler.note_saved()
            if SAVE_NS is not None:
                SAVE_NS.observe(time.monotonic_ns() - t0)
            if call_wrapper.state.active_rank == 0:
                # durable progress == last save: a fault rewinds to here
                write_progress_iteration(ckpt, step + 1)
        kind = faults.get(str(step))
        if kind and claim_fault(step):
            print(f"soak[{rank}] {kind} at step {step}", flush=True)
            if kind == "crash":
                os._exit(41)
            if kind == "hang":
                time.sleep(3600)
            raise RuntimeError(f"scheduled exception step {step}")
    return "done"

print(f"soak[{rank}] result={run()}", flush=True)
"""


def _gen_fault_schedule(seed, nproc, horizon, probs, shift_at=None,
                        shift_mult=1.0):
    """Pre-draw the whole injection timeline: ``probs`` maps fault kind ->
    per-step probability; from ``shift_at`` on, every probability is
    multiplied by ``shift_mult`` (the fault-regime step the adaptive
    policy must chase).  Same seed -> byte-identical schedule."""
    rng = random.Random(seed)
    faults: dict = {str(r): {} for r in range(nproc)}
    for r in range(nproc):
        for step in range(1, horizon):
            mult = (
                shift_mult
                if shift_at is not None and step >= shift_at
                else 1.0
            )
            draw = rng.random()
            for kind, p_kind in probs.items():
                if draw < p_kind * mult:
                    faults[str(r)][str(step)] = kind
                    break
                draw -= p_kind * mult
    return {
        "seed": seed,
        "nproc": nproc,
        "horizon": horizon,
        "shift_at": shift_at,
        "shift_mult": shift_mult,
        "faults": faults,
    }


def _run_fault_shift_ab(args) -> None:
    """Adaptive-vs-fixed goodput A/B: both arms replay ONE seeded fault
    schedule (same injection timeline) for the same wall time; goodput is
    durably-saved progress.  Reports ``policy_goodput_gain`` =
    adaptive / fixed, gated at 1.1x (waived on 1-core hosts, where two
    gangs + monitors thrash a single CPU)."""
    workdir = tempfile.mkdtemp(prefix="tpurx-soak-ab-")
    sched_path = args.fault_schedule
    if sched_path is None:
        seed = args.fault_seed if args.fault_seed is not None else 0x600D
        step_s = 0.02
        horizon = max(400, int(args.seconds / step_s) * 2)
        sched = _gen_fault_schedule(
            seed, args.nproc, horizon, {"exception": 0.004},
            shift_at=horizon // 4, shift_mult=6.0,
        )
        sched_path = os.path.join(workdir, "fault_schedule.json")
        with open(sched_path, "w") as f:
            json.dump(sched, f)
    arms: dict = {}
    for arm in ("fixed", "adaptive"):
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--seconds", str(args.seconds),
            "--nproc", str(args.nproc),
            "--fault-schedule", os.path.abspath(sched_path),
            "--goodput-arm", arm,
        ]
        env = dict(os.environ)
        env.update({
            "TPURX_POLICY": "1" if arm == "adaptive" else "0",
            "TPURX_POLICY_INTERVAL_S": "2.0",
            # the Young/Daly optimum here lives in single-digit seconds;
            # production clamp floors would pin the controller
            "TPURX_POLICY_CADENCE_MIN_S": "0.5",
            "TPURX_POLICY_CADENCE_MAX_S": "60.0",
        })
        proc = subprocess.run(cmd, cwd=REPO, env=env,
                              capture_output=True, text=True)
        last = [l for l in proc.stdout.splitlines() if l.startswith("{")]
        arms[arm] = (
            json.loads(last[-1]) if last
            else {"ok": False, "final_progress": 0}
        )
        print(f"soak-ab[{arm}]: final={arms[arm].get('final_progress')} "
              f"ok={arms[arm].get('ok')}", flush=True)
    fixed_g = max(1, int(arms["fixed"].get("final_progress") or 0))
    adaptive_g = int(arms["adaptive"].get("final_progress") or 0)
    gain = adaptive_g / fixed_g
    waived = (os.cpu_count() or 1) <= 1
    arms_ok = bool(arms["fixed"].get("ok") and arms["adaptive"].get("ok"))
    ok = arms_ok and (waived or gain >= 1.1)
    print(json.dumps({
        "metric": "soak_fault_shift",
        "seconds_per_arm": args.seconds,
        "fault_schedule": os.path.abspath(sched_path),
        "adaptive_progress": adaptive_g,
        "fixed_progress": fixed_g,
        "policy_goodput_gain": round(gain, 3),
        "policy_gate_waived": waived,
        "arms_ok": arms_ok,
        "ok": ok,
    }))
    sys.exit(0 if ok else 1)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class StoreChaos(threading.Thread):
    """Kill and restart the external control plane — at random intervals,
    or (``trigger`` given) the moment the trigger fires, so kills can be
    TARGETED inside a save window (store-outage-mid-save)."""

    def __init__(self, spawn_fn, min_s: float, max_s: float, down_s: float,
                 trigger=None):
        super().__init__(daemon=True)
        self.spawn_fn = spawn_fn
        self.min_s, self.max_s, self.down_s = min_s, max_s, down_s
        self.trigger = trigger
        self.proc = spawn_fn()
        self.kills = 0
        self._halt = threading.Event()
        self.rng = random.Random(0xC4A05)

    def _wait_for_next_kill(self) -> bool:
        """True when a kill is due; False when halting."""
        if self.trigger is None:
            return not self._halt.wait(self.rng.uniform(self.min_s, self.max_s))
        while not self._halt.is_set():
            if self.trigger():
                return True
            if self._halt.wait(0.2):
                break
        return False

    def run(self):
        while not self._halt.is_set():
            if not self._wait_for_next_kill():
                break
            try:
                os.kill(self.proc.pid, signal.SIGKILL)
                self.proc.wait(timeout=10)
            except (OSError, subprocess.TimeoutExpired):
                pass
            self.kills += 1
            print(f"soak: store host KILLED (#{self.kills})", flush=True)
            if self._halt.wait(self.down_s):
                break
            self.proc = self.spawn_fn()
            print("soak: store host restarted", flush=True)

    def stop(self):
        # join BEFORE terminating: run() may be mid-respawn, and killing the
        # old proc while it assigns a fresh one would leak an orphan store
        self._halt.set()
        self.join(timeout=15)
        try:
            self.proc.terminate()
            self.proc.wait(timeout=10)
        except (OSError, subprocess.TimeoutExpired):
            try:
                self.proc.kill()
            except OSError:
                pass


def _ring_latencies(events):
    """Derive detect->recover latencies (ms) for both rings from the JSONL.

    Outer: FAILURE_DETECTED -> next WORKER_STARTED recorded by the SAME pid
    (the launcher records both; the wrapper's worker_started is a worker
    pid and never pairs).
    Inner: earliest DETECTION event in a worker pid (HANG_DETECTED from the
    quorum tripwire, INPROCESS_INTERRUPTED for exceptions;
    INPROCESS_RESTART_STARTED as the fallback anchor) ->
    INPROCESS_RESTART_COMPLETED in the same pid, so a detection-latency
    regression moves the measured number, not just teardown+re-entry.
    """
    outer, inner = [], []
    pending_outer = None
    pending_inner = {}
    for ev in events:
        name = ev.get("event")
        if name == "failure_detected" and pending_outer is None:
            pending_outer = ev["mono_ns"], ev["pid"]
        elif name == "worker_started" and pending_outer is not None:
            t0, pid = pending_outer
            if ev["pid"] == pid and ev["mono_ns"] > t0:
                outer.append((ev["mono_ns"] - t0) / 1e6)
                pending_outer = None
        elif name in ("hang_detected", "inprocess_interrupted",
                      "inprocess_restart_started"):
            # setdefault keeps the EARLIEST anchor: real detection when
            # recorded, restart entry otherwise
            pending_inner.setdefault(ev["pid"], ev["mono_ns"])
        elif name == "inprocess_restart_completed":
            t0 = pending_inner.pop(ev["pid"], None)
            if t0 is not None and ev["mono_ns"] > t0:
                inner.append((ev["mono_ns"] - t0) / 1e6)
    return outer, inner


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seconds", type=float, default=120.0)
    p.add_argument("--gate", action="store_true",
                   help="the regression gate: 900s, chaos-store, quorum")
    p.add_argument("--exc-p", type=float, default=0.01)
    p.add_argument("--crash-p", type=float, default=0.008)
    p.add_argument("--hang-p", type=float, default=0.004)
    p.add_argument("--qstall-p", type=float, default=0.006)
    p.add_argument("--cwedge-p", type=float, default=0.004,
                   help="wedged-collective injection probability "
                        "(quorum-armed runs only)")
    p.add_argument("--save-every", type=int, default=0,
                   help="steps between rank-0 store-backed saves (0=off)")
    p.add_argument("--store-kill-mid-save", action="store_true",
                   help="target store kills INSIDE save windows; asserts "
                        "every started save still completes")
    p.add_argument("--corrupt-blob", choices=("bitflip", "truncate"),
                   help="checkpoint-integrity campaign: corrupt every copy "
                        "of the newest local-checkpoint iteration mid-run; "
                        "the restarted gang must fallback-restore the "
                        "next-oldest valid iteration")
    p.add_argument("--peer-mem-kill", action="store_true",
                   help="warm-restore campaign: stall the peer-memory "
                        "serving rank mid-restore drill; the other ranks' "
                        "ladders must fall through to their own disk with "
                        "fallback depth 0")
    p.add_argument("--ramp-degrade", action="store_true",
                   help="predict-and-evacuate campaign: one rank's health "
                        "and straggler scores ramp worse round by round; "
                        "the policy's fused rank risk must EVACUATE it "
                        "(checkpoint-ahead, published record, peer "
                        "warm-join with zero disk bytes) before its "
                        "hard-fault deadline, and never evacuate a "
                        "healthy rank")
    p.add_argument("--link-degrade", action="store_true",
                   help="self-healing-collectives campaign: one rank's "
                        "primary collective lane is fault-armed to stall "
                        "past its deadline (TPURX_FAULT=coll_stall); the "
                        "wrapper must degrade (retry -> re-layout) and the "
                        "job must finish with ZERO launcher-ring restarts")
    p.add_argument("--store-longpoll-abort", action="store_true",
                   help="interruptible-long-poll campaign: each restart "
                        "episode parks one rank in a server-held store "
                        "wait() and injects a sibling fault; the gate "
                        "asserts the async abort LANDS on the parked rank "
                        "within the poll-quantum contract and that no rank "
                        "ever exits ret=None")
    p.add_argument("--longpoll-bound-s", type=float, default=None,
                   help="bound on injection->landing latency per episode "
                        "(default: abort-propagation budget + 2x "
                        "TPURX_STORE_POLL_S)")
    p.add_argument("--fault-seed", type=int, default=None,
                   help="derive a deterministic per-(rank,step) fault "
                        "schedule from this seed and replay it (each "
                        "scheduled injection fires exactly once) instead "
                        "of per-step RNG draws")
    p.add_argument("--fault-schedule", default=None,
                   help="replay an exact recorded schedule file "
                        "(overrides --fault-seed generation)")
    p.add_argument("--fault-shift", action="store_true",
                   help="adaptive-vs-fixed goodput A/B under ONE seeded "
                        "fault schedule whose fault rate steps up "
                        "mid-run; reports policy_goodput_gain")
    p.add_argument("--goodput-arm", choices=("adaptive", "fixed"),
                   default=None, help=argparse.SUPPRESS)  # one A/B arm
    p.add_argument("--nproc", type=int, default=2)
    p.add_argument("--native-store", action="store_true")
    p.add_argument("--chaos-store", action="store_true",
                   help="external journaled control plane, randomly killed")
    p.add_argument("--quorum", action="store_true",
                   help="arm the on-device quorum tripwire in the workload")
    p.add_argument("--store-kill-every", type=float, nargs=2,
                   default=(35.0, 70.0), metavar=("MIN", "MAX"))
    p.add_argument("--store-down", type=float, default=3.0)
    p.add_argument("--inner-bound-ms", type=float, default=8000.0,
                   help="bound on median inner-ring detect->recover")
    p.add_argument("--outer-bound-ms", type=float, default=30000.0,
                   help="bound on median outer-ring detect->recover")
    args = p.parse_args()
    if args.gate:
        args.seconds = max(args.seconds, 900.0)
        args.chaos_store = True
        args.quorum = True
        args.store_kill_mid_save = True
        if not args.save_every:
            args.save_every = 60
    if args.store_kill_mid_save:
        args.chaos_store = True
        if not args.save_every:
            args.save_every = 40
    if args.fault_shift:
        _run_fault_shift_ab(args)
        return

    workdir = tempfile.mkdtemp(prefix="tpurx-soak-")
    wl_path = os.path.join(workdir, "workload.py")
    with open(wl_path, "w") as f:
        if args.goodput_arm:
            f.write(WORKLOAD_GOODPUT)
        elif args.store_longpoll_abort:
            f.write(WORKLOAD_LONGPOLL)
        elif args.ramp_degrade:
            f.write(WORKLOAD_EVAC)
        elif args.link_degrade:
            f.write(WORKLOAD_COLL)
        elif args.corrupt_blob or args.peer_mem_kill:
            f.write(WORKLOAD_LCKPT)
        else:
            f.write(WORKLOAD)
    ckpt = os.path.join(workdir, "progress.txt")
    profile = os.path.join(workdir, "profile.jsonl")
    journal = os.path.join(workdir, "store.journal")
    port = _free_port()

    env = dict(os.environ)
    disarm_platform_sitecustomize(env)
    env.update(
        {
            "TPURX_REPO": REPO,
            "SOAK_CKPT": ckpt,
            "SOAK_EXC_P": str(args.exc_p),
            "SOAK_CRASH_P": str(args.crash_p),
            "SOAK_HANG_P": str(args.hang_p),
            "SOAK_QSTALL_P": str(args.qstall_p if args.quorum else 0.0),
            "SOAK_CWEDGE_P": str(args.cwedge_p if args.quorum else 0.0),
            "SOAK_SAVE_EVERY": str(args.save_every),
            "SOAK_QUORUM": "1" if args.quorum else "0",
            "TPURX_PROFILING_FILE": profile,
            "TPURX_FT_ENABLE_DEVICE_HEALTH_CHECK": "0",
            "TPURX_FT_RANK_HEARTBEAT_TIMEOUT": "3.0",
            "TPURX_FT_INITIAL_RANK_HEARTBEAT_TIMEOUT": "60.0",
            "TPURX_FT_WORKLOAD_CHECK_INTERVAL": "0.2",
            "TPURX_FT_WORKERS_STOP_TIMEOUT": "3.0",
            "TPURX_FT_MAX_NO_PROGRESS_CYCLES": "0",  # chaos: no early stop
            "TPURX_FT_STORE_REJOIN_WINDOW": "120.0",
            "JAX_PLATFORMS": "cpu",
        }
    )
    sched_path = args.fault_schedule
    if sched_path is None and (args.fault_seed is not None or args.goodput_arm):
        sched = _gen_fault_schedule(
            args.fault_seed if args.fault_seed is not None else 0x600D,
            args.nproc, 20000,
            {"exception": args.exc_p, "crash": args.crash_p,
             "hang": args.hang_p},
        )
        sched_path = os.path.join(workdir, "fault_schedule.json")
        with open(sched_path, "w") as f:
            json.dump(sched, f)
    if sched_path is not None:
        fired = os.path.join(workdir, "fault_fired")
        os.makedirs(fired, exist_ok=True)
        env["SOAK_FAULT_SCHEDULE"] = os.path.abspath(sched_path)
        env["SOAK_FAULT_FIRED_DIR"] = fired
        with open(sched_path) as f:
            n_sched = sum(len(v) for v in json.load(f)["faults"].values())
        print(f"soak: replaying fault schedule {sched_path} "
              f"({n_sched} scheduled injections)", flush=True)
    if args.corrupt_blob or args.peer_mem_kill:
        env.update({
            "SOAK_CKPT_ROOT": os.path.join(workdir, "lckpt"),
            "SOAK_LCKPT_EVERY": "10",
            # barriers/replication pause heartbeats briefly; keep the kill
            # threshold clear of normal collective latency
            "TPURX_FT_RANK_HEARTBEAT_TIMEOUT": "10.0",
        })
    if args.corrupt_blob:
        env.update({
            "SOAK_CORRUPT_MODE": args.corrupt_blob,
            "SOAK_CORRUPT_STEP": "35",
        })
    if args.peer_mem_kill:
        env.update({
            "SOAK_PEER_DRILL_STEP": "25",
            # arm the stall fault on the SERVING rank only: rank 0 keeps
            # its resident copy (so its advert attracts probes) but drops
            # every peer-memory request it receives
            "TPURX_FAULT": "peer_mem_stall",
            "TPURX_FAULT_RANKS": "0",
            "TPURX_CKPT_PEER_MEM_TIMEOUT": "2.0",
        })
        if not args.corrupt_blob:
            env["SOAK_CORRUPT_STEP"] = "-1"  # drill only, no corruption leg
    if args.ramp_degrade:
        env.update({
            "SOAK_CKPT_ROOT": os.path.join(workdir, "lckpt"),
            "SOAK_LCKPT_EVERY": "5",
            "SOAK_EVAC_VICTIM": "1",
            "SOAK_EVAC_RAMP_ROUNDS": "12",
            "SOAK_EVAC_DEADLINE": "45",
            "TPURX_EVAC": "1",
            # saves/tree rounds/joins pause heartbeats briefly
            "TPURX_FT_RANK_HEARTBEAT_TIMEOUT": "15.0",
        })
    lp_poll_s = 0.25
    if args.store_longpoll_abort:
        env.update({
            # a visible (but short) quantum so the landing-latency numbers
            # actually exercise the slicing, not sub-millisecond noise
            "TPURX_STORE_POLL_S": str(lp_poll_s),
            "SOAK_LP_INJECT_DELAY": "2.0",
            # the parked rank legitimately skips rank-monitor heartbeats
            # while inside wait(); keep the outer ring's kill threshold
            # clear of a whole episode
            "TPURX_FT_RANK_HEARTBEAT_TIMEOUT": "30.0",
        })
    if args.link_degrade:
        env.update({
            # stall rank 0's PRIMARY collective lane past its deadline;
            # fallback lanes stay healthy so the degrade ladder can land
            "TPURX_FAULT": "coll_stall",
            "TPURX_FAULT_RANKS": "0",
            "TPURX_COLL_DEADLINE_MS": "300",
            "TPURX_COLL_RETRIES": "1",
            "SOAK_COLL_STEPS": "25",
            # the first degraded call eats ~2 deadlines + a re-layout;
            # keep the heartbeat kill threshold well clear of that
            "TPURX_FT_RANK_HEARTBEAT_TIMEOUT": "10.0",
        })
    if args.quorum:
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    if args.native_store:
        env["TPURX_NATIVE_STORE"] = "1"

    chaos = None
    chunks: list = []   # launcher stdout, drained continuously (shared with
    # the mid-save trigger, which scans it for save-start markers)
    launch_cmd = [
        sys.executable, "-m", "tpu_resiliency.fault_tolerance.launcher",
        "--nnodes", "1", "--nproc-per-node", str(args.nproc),
        "--rdzv-endpoint", f"127.0.0.1:{port}",
        "--max-restarts", "0",   # unlimited
        "--monitor-interval", "0.05",
    ]
    if args.chaos_store:
        def spawn_store():
            cmd = [
                sys.executable, "-m",
                "tpu_resiliency.fault_tolerance.control_plane",
                "--host", "127.0.0.1", "--port", str(port),
                "--journal", journal,
            ]
            if args.native_store:
                cmd.append("--native-store")
            return subprocess.Popen(cmd, env=env, cwd=REPO,
                                    stdout=subprocess.DEVNULL,
                                    stderr=subprocess.STDOUT)

        trigger = None
        if args.store_kill_mid_save:
            state = {"last": 0, "next_kill_t": 0.0}

            def trigger():
                # fire INSIDE a save window: a fresh "save start" marker,
                # rate-limited so some saves also complete undisturbed
                starts = "".join(chunks).count("] save start at step")
                now = time.monotonic()
                if starts > state["last"]:
                    state["last"] = starts
                    if starts % 2 == 1 and now >= state["next_kill_t"]:
                        state["next_kill_t"] = now + 12.0
                        return True
                return False

        chaos = StoreChaos(spawn_store, *args.store_kill_every,
                           down_s=args.store_down, trigger=trigger)
        time.sleep(2.0)  # let the control plane bind before launchers dial
    else:
        launch_cmd.append("--host-store")
    launch_cmd.append(wl_path)

    proc = subprocess.Popen(
        launch_cmd, cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    # drain stdout continuously: a full 64KB pipe would block the launcher
    # and wedge the very run being measured

    def _drain():
        for line in proc.stdout:
            chunks.append(line)

    reader = threading.Thread(target=_drain, daemon=True)
    reader.start()
    if chaos is not None:
        chaos.start()
    deadline = time.monotonic() + args.seconds
    progress_samples = []
    while time.monotonic() < deadline and proc.poll() is None:
        time.sleep(5.0)
        try:
            progress_samples.append(int(open(ckpt).read().strip() or 0))
        except OSError:
            progress_samples.append(0)
    proc.terminate()
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()  # never leak the launcher tree from the soak itself
        proc.wait(timeout=10)
    if chaos is not None:
        chaos.stop()
    reader.join(timeout=10)
    out = "".join(chunks)

    events = []
    try:
        with open(profile) as f:
            for line in f:
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    events.sort(key=lambda e: e.get("mono_ns", 0))
    outer_ms, inner_ms = _ring_latencies(events)

    def med(xs):
        return round(sorted(xs)[len(xs) // 2], 1) if xs else None

    # restart cycles = launcher-recorded worker (re)starts beyond the first
    # (the launcher records worker_started in ITS pid; wrapper copies are
    # worker pids)
    cycles = max(0, sum(
        1 for ev in events
        if ev.get("event") == "worker_started" and ev.get("pid") == proc.pid
    ) - 1)
    injected = {
        "crashes": out.count("] crash at step"),
        "hangs": out.count("] hang at step"),
        "exceptions": out.count("] exception at step"),
        "quorum_stalls": out.count("] quorum stall at step"),
        "collective_wedges": out.count("] collective wedge at step"),
    }
    monotone = all(b >= a for a, b in zip(progress_samples, progress_samples[1:]))
    final = progress_samples[-1] if progress_samples else 0
    bounds_ok = True
    if inner_ms and not (med(inner_ms) <= args.inner_bound_ms):
        bounds_ok = False
    if outer_ms and not (med(outer_ms) <= args.outer_bound_ms):
        bounds_ok = False
    inner_faults = (injected["exceptions"] + injected["quorum_stalls"]
                    + injected["collective_wedges"])
    # faults were injected -> the matching ring must actually have run
    rings_ok = (
        (inner_faults == 0 or inner_ms)
        and (injected["crashes"] + injected["hangs"] == 0 or cycles >= 1)
    )
    # abort-ladder stage outcomes from the profiling stream: every inner
    # trip runs the ladder, whose fingerprint rung must have released
    stage_outcomes: dict = {}
    for ev in events:
        if ev.get("event") == "abort_stage":
            key = f"{ev.get('stage')}/{ev.get('outcome')}"
            stage_outcomes[key] = stage_outcomes.get(key, 0) + 1
    ladder_ok = (
        inner_faults == 0 or not inner_ms
        or stage_outcomes.get("fingerprint/released", 0) >= 1
    )
    # store-outage-mid-save: every save that started must have completed
    # (the unified retry policy rides out the kill); tolerated shortfalls:
    # one save aborted per worker restart (either ring) plus the single
    # save the soak's own deadline may cut off in flight
    saves_started = out.count("] save start at step")
    saves_done = out.count("] save done at step")
    saves_ok = True
    if args.store_kill_mid_save:
        tolerance = cycles + len(inner_ms) + 1
        saves_ok = (
            saves_started >= 1
            and saves_done >= max(1, saves_started - tolerance)
        )
    # checkpoint-integrity campaign (--corrupt-blob): the corrupt blobs must
    # be detected + quarantined and EVERY rank must fallback-restore an
    # iteration strictly older than the corrupted one
    # warm-restore campaign (--peer-mem-kill): every non-serving rank's
    # drill must have been served from its OWN DISK (peer rung timed out
    # against the stalled server) at fallback depth 0 — the stall degrades
    # the restore to a colder source, never to an older iteration
    peer_report: dict = {}
    peer_ok = True
    if args.peer_mem_kill:
        import re as re_mod

        drills = [
            tuple(int(x) for x in m)
            for m in re_mod.findall(
                r"soaklc\[(\d+)\] peer-drill it=(\d+) disk_b=(\d+) "
                r"peer_b=(\d+) depth=(\d+)", out)
        ]
        nonserving = [d for d in drills if d[0] != 0]
        peer_ok = bool(
            drills
            and {d[0] for d in drills} == set(range(args.nproc))
            and nonserving
            and all(disk > 0 and peer == 0 and depth == 0
                    for _r, _it, disk, peer, depth in nonserving)
        )
        peer_report = {
            "peer_mem_kill": True,
            "peer_drills": drills,
            "peer_ok": peer_ok,
        }
        if not args.corrupt_blob:
            # lckpt workloads track progress through checkpoint iterations
            monotone = True
            final = max((d[1] for d in drills), default=0)
    # self-healing-collectives campaign (--link-degrade): every rank must
    # FINISH (no restart of any kind), the armed rank must have walked the
    # degrade ladder (timeouts then degrades both nonzero), the healthy
    # ranks must have degraded nothing, and the launcher ring must have
    # recorded ZERO restart cycles — a single bad link costs one
    # collective's deadline plus a local re-layout, not a pod-wide restart
    coll_report: dict = {}
    coll_ok = True
    if args.link_degrade:
        import re as re_mod

        marks = [
            tuple(int(x) for x in m)
            for m in re_mod.findall(
                r"soakcoll\[(\d+)\] result=done degrades=(\d+) "
                r"timeouts=(\d+)", out)
        ]
        armed = [m for m in marks if m[0] == 0]
        coll_ok = bool(
            marks
            and {m[0] for m in marks} == set(range(args.nproc))
            and armed and armed[0][1] >= 1 and armed[0][2] >= 1
            # healthy ranks may eat a first-call compile-latency timeout
            # (retry rung absorbs it) but must never DEGRADE
            and all(m[1] == 0 for m in marks if m[0] != 0)
            and cycles == 0
        )
        coll_report = {
            "link_degrade": True,
            "coll_marks": marks,
            "coll_degrades": armed[0][1] if armed else 0,
            "coll_timeouts": armed[0][2] if armed else 0,
            "coll_ok": coll_ok,
        }
        monotone = all(
            b >= a for a, b in zip(progress_samples, progress_samples[1:])
        )
        final = len(marks)
    # predict-and-evacuate campaign (--ramp-degrade): the fused rank risk
    # must evacuate the ramping victim BEFORE its hard-fault deadline and
    # never touch a healthy rank, and the victim's slot must warm-join
    # chunk-granular off peer memory (zero disk bytes, no global restore)
    evac_report: dict = {}
    evac_ok = True
    if args.ramp_degrade:
        import re as re_mod

        evacs = [
            (int(r), int(s))
            for r, s in re_mod.findall(
                r"soakev\[0\] EVACUATE rank=(\d+) at step=(\d+)", out)
        ]
        joins = [
            (r, int(it), int(pb), int(db))
            for r, it, pb, db in re_mod.findall(
                r"soakev\[\d+\] JOIN warm=(\w+) iter=(\d+) peer_b=(\d+) "
                r"disk_b=(\d+)", out)
        ]
        hard_faults = out.count("] HARD FAULT at step")
        done = len(re_mod.findall(r"soakev\[\d+\] result=done joined=True",
                                  out))
        victim_rank = 1
        evac_ok = bool(
            evacs
            and {r for r, _s in evacs} == {victim_rank}  # nobody healthy
            and hard_faults == 0                # fired before the deadline
            and joins
            and all(w == "True" and pb > 0 and db == 0
                    for w, _it, pb, db in joins)
            and done == args.nproc
        )
        evac_report = {
            "ramp_degrade": True,
            "evacuations": evacs,
            "evac_joins": joins,
            "hard_faults": hard_faults,
            "evac_ok": evac_ok,
        }
        monotone = True
        final = done
    # interruptible-long-poll campaign (--store-longpoll-abort): every
    # injection must LAND on the parked rank (landed marker for the same
    # episode) within the abort-propagation budget plus 2x the poll quantum
    # — and no rank may ever exit ret=None (the restart completes instead
    # of silently swallowing the raise inside an uninterruptible recv)
    lp_report: dict = {}
    lp_ok = True
    if args.store_longpoll_abort:
        import re as re_mod

        def _marks(kind):
            return {
                int(ep): float(t)
                for ep, t in re_mod.findall(
                    rf"soaklp\[\d+\] {kind} ep=(\d+) t=([0-9.]+)", out)
            }

        parks, injects, landings = (_marks("park"), _marks("inject"),
                                    _marks("landed"))
        land_ms = sorted(
            (landings[ep] - injects[ep]) * 1000.0
            for ep in landings if ep in injects
        )
        # budget: the injector's raise propagates through its wrapper's
        # abort broadcast and the victim's monitor thread before the async
        # raise is even ISSUED; only then does the poll-quantum contract
        # (2x TPURX_STORE_POLL_S) apply to the landing itself
        bound_s = (args.longpoll_bound_s if args.longpoll_bound_s is not None
                   else 4.0 + 2 * lp_poll_s)
        # the last episode may be cut off mid-park by the soak deadline
        complete = [ep for ep in injects if ep in landings]
        lp_ok = bool(
            len(injects) >= 1
            and len(complete) >= max(1, len(injects) - 1)
            and land_ms
            and max(land_ms) <= bound_s * 1000.0
            and "ret=None" not in out
            and "result=None" not in out
        )
        lp_report = {
            "store_longpoll_abort": True,
            "lp_episodes_injected": len(injects),
            "lp_episodes_landed": len(landings),
            "lp_land_ms": [round(x, 1) for x in land_ms],
            "lp_land_ms_median": (round(land_ms[len(land_ms) // 2], 1)
                                  if land_ms else None),
            "lp_bound_ms": bound_s * 1000.0,
            "lp_ret_none": out.count("ret=None") + out.count("result=None"),
            "lp_ok": lp_ok,
        }
        monotone = True  # no progress file in this campaign
        final = len(landings)
    ckpt_report: dict = {}
    ckpt_ok = True
    if args.corrupt_blob:
        import glob as glob_mod
        import re as re_mod

        corrupted = re_mod.findall(
            r"soaklc\[\d+\] corrupted newest mode=\S+ files=(\d+) "
            r"iters=iter_(\d+)", out)
        restores = [
            tuple(int(x) for x in m)
            for m in re_mod.findall(
                r"soaklc\[(\d+)\] restored iter=(\d+) depth=(\d+) "
                r"corrupt=(\d+) quarantined=(\d+) debris=(\d+)", out)
        ]
        # end-of-run debris is best-effort (keep_last pruning legitimately
        # reclaims quarantined iter dirs); the restore-time debris count in
        # each marker is the authoritative check
        end_debris = glob_mod.glob(
            os.path.join(workdir, "lckpt", "**", "*.corrupt"), recursive=True)
        corrupted_iter = int(corrupted[0][1]) if corrupted else None
        fb = [r for r in restores if r[2] >= 1]
        ckpt_ok = bool(
            corrupted and int(corrupted[0][0]) >= 1
            and fb
            and {r[0] for r in fb} == set(range(args.nproc))
            and all(it < corrupted_iter for _r, it, _d, _c, _q, _f in fb)
            and all(c >= 1 and q >= 1 and f >= 1
                    for _r, _it, _d, c, q, f in fb)
        )
        ckpt_report = {
            "corrupt_blob": args.corrupt_blob,
            "corrupted_iter": corrupted_iter,
            "restores": restores,
            "fallback_restores": fb,
            "quarantine_debris_at_exit": len(end_debris),
            "ckpt_ok": ckpt_ok,
        }
        # the lckpt workload tracks progress through checkpoint iterations,
        # not the progress file — those checks don't apply
        monotone = True
        final = max((r[1] for r in restores), default=0)
    if args.store_longpoll_abort:
        ok = bool(lp_ok)
    elif args.ramp_degrade:
        ok = bool(evac_ok)
    elif args.corrupt_blob:
        ok = bool(ckpt_ok and peer_ok and cycles >= 1)
    elif args.link_degrade:
        ok = bool(coll_ok and monotone)
    elif args.peer_mem_kill:
        ok = bool(peer_ok and final > 0)
    else:
        ok = bool(monotone and final > 0 and bounds_ok and rings_ok
                  and ladder_ok and saves_ok)
    print(
        json.dumps(
            {
                "metric": "soak_launcher",
                "seconds": args.seconds,
                "chaos_store": args.chaos_store,
                "store_kills": chaos.kills if chaos else 0,
                "quorum": args.quorum,
                "final_progress": final,
                "progress_samples": progress_samples[-12:],
                "cycles": cycles,
                "injected": injected,
                "inner_ring_recoveries": len(inner_ms),
                "inner_detect_to_recover_ms_median": med(inner_ms),
                "outer_ring_recoveries": len(outer_ms),
                "outer_detect_to_recover_ms_median": med(outer_ms),
                "abort_stage_outcomes": stage_outcomes,
                "saves_started": saves_started,
                "saves_done": saves_done,
                "monotone_progress": monotone,
                "bounds_ok": bounds_ok,
                "ladder_ok": ladder_ok,
                "saves_ok": saves_ok,
                **coll_report,
                **peer_report,
                **evac_report,
                **lp_report,
                **ckpt_report,
                "ok": ok,
            }
        )
    )
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
