"""Control-plane scalability benchmark: store throughput + barrier latency.

The reference's scalability headline is a 0.5s TCPStore barrier at 16,384
ranks (BASELINE.md).  This measures our store servers on one host:
small-op throughput per client, aggregate multi-client throughput, and
N-participant barrier completion latency, for both the asyncio and native
C++ servers.  Prints one JSON line per server.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_resiliency.store import StoreClient, StoreServer, barrier


def bench_server(server, label, n_clients=64, ops_per_client=200):
    port = server.port
    # aggregate ADD throughput
    def worker(i, out):
        c = StoreClient("127.0.0.1", port)
        t0 = time.perf_counter()
        for _ in range(ops_per_client):
            c.add(f"ctr{i % 8}", 1)
        out[i] = time.perf_counter() - t0
        c.close()

    times = {}
    threads = [threading.Thread(target=worker, args=(i, times)) for i in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    agg_ops = n_clients * ops_per_client / wall

    # barrier latency with n_clients participants
    lat = {}

    def member(i):
        c = StoreClient("127.0.0.1", port)
        t0 = time.perf_counter()
        barrier(c, "bench_barrier", n_clients, timeout=60.0)
        lat[i] = time.perf_counter() - t0
        c.close()

    threads = [threading.Thread(target=member, args=(i,)) for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    barrier_ms = max(lat.values()) * 1000.0

    print(
        json.dumps(
            {
                "metric": f"store_{label}",
                "clients": n_clients,
                "agg_ops_per_s": round(agg_ops),
                "barrier_ms": round(barrier_ms, 1),
            }
        )
    )


def main():
    n_clients = int(os.environ.get("BENCH_CLIENTS", "64"))
    py_server = StoreServer(host="127.0.0.1", port=0).start_in_thread()
    bench_server(py_server, "asyncio", n_clients=n_clients)
    py_server.stop()
    try:
        from tpu_resiliency.store.native import NativeStoreServer

        native = NativeStoreServer(host="127.0.0.1", port=0).start()
        bench_server(native, "native_cpp", n_clients=n_clients)
        native.stop()
    except Exception as exc:  # noqa: BLE001
        print(json.dumps({"metric": "store_native_cpp", "error": str(exc)}))


if __name__ == "__main__":
    main()
