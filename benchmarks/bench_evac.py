"""Predict-and-evacuate vs react-after-failure goodput (ISSUE 18 gate).

A seeded discrete-event simulation of a training gang where nodes
degrade BEFORE they die — health worsens and step time stretches over a
ramp window, then the node hard-faults — driving the REAL policy stack
end to end:

- each control tick feeds per-rank :class:`RankSignals` (ramping victim
  + noisy healthy ranks) through a real :class:`PolicyController` over a
  scripted feed with ``TPURX_EVAC=1``: the fused
  :class:`RankRiskModel` score, the consecutive-tick streak guard, the
  hysteresis re-arm latch and the one-shot :class:`Actuator` action are
  all the production code paths;
- the **evacuate arm** pays the planned-handoff cost when the controller
  fires before the hard fault (checkpoint-ahead save + spare promotion +
  peer warm join — seconds) and loses NO work; a miss falls back to the
  reactive cost;
- the **react arm** ignores the leading indicators and pays the full
  reactive episode at fault time: detection + restart ladder + cold
  global restore + the uncommitted tail back to the last cadence save.

Gates: mean ``evac_goodput_gain`` >= 1.1 over the trials (waived on
1-core hosts, matching the soak lanes), ZERO healthy-rank evacuations
(the noisy healthy ranks are the false-positive bait), and zero missed
ramps.  Also reports ``evac_join_mttr_ms`` — the risk-cross → join-done
handoff time.  Deterministic: same seed, same verdict on every host.

Emits one JSON line:  python benchmarks/bench_evac.py [--seed N]
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import random
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpu_resiliency.policy import (  # noqa: E402
    EstimatorInputs, GoodputEstimator, PolicyController, RankSignals,
    set_evacuation_handler,
)
from tpu_resiliency.utils import env  # noqa: E402

TOTAL_S = 6000.0
TICK_S = 5.0
N_HEALTHY = 4           # steady ranks: the false-positive bait
DEGRADE_MTBF_S = 600.0  # mean time between degradation onsets
RAMP_S = 120.0          # onset -> hard fault

# reactive episode: detect + restart ladder + cold global restore, plus
# the uncommitted tail back to the last cadence save (mean interval/2)
REACT_DETECT_S = 10.0
REACT_RESTART_S = 30.0
REACT_COLD_RESTORE_S = 25.0
CKPT_INTERVAL_S = 60.0

# planned handoff: out-of-cadence checkpoint-ahead + CAS'd spare
# promotion + chunk-granular peer warm join (no lost work: the
# checkpoint-ahead committed the tail before the slot went away)
EVAC_CKPT_AHEAD_S = 8.0
EVAC_PROMOTE_S = 1.0
EVAC_JOIN_S = (4.0, 9.0)  # seeded jitter range


def draw_degradations(seed: int) -> list:
    """Sorted onset times of node degradations; each ramps ``RAMP_S``
    then hard-faults.  Deterministic in ``seed``."""
    rng = random.Random(seed)
    onsets = []
    t = 0.0
    while True:
        t += rng.expovariate(1.0 / DEGRADE_MTBF_S)
        if t + RAMP_S >= TOTAL_S:
            return onsets
        onsets.append(t)


def _healthy_signals(rng: random.Random) -> dict:
    """Noisy-but-fine ranks: flutter that must never cross the trigger."""
    return {
        r: RankSignals(
            health_score=rng.uniform(0.0, 0.25),
            straggler_score=rng.uniform(0.9, 1.0),
        )
        for r in range(N_HEALTHY)
    }


def run_trial(seed: int) -> dict:
    """One seeded schedule through both arms; returns the per-trial row."""
    onsets = draw_degradations(seed)
    rng = random.Random(seed ^ 0xE7AC)

    # -- evacuate arm: the real controller over scripted per-rank signals
    evacuated_at: dict = {}

    def on_evacuate(victim_rank, reason):
        evacuated_at[victim_rank] = True

    class _SimFeed:
        """collect() returns the inputs staged for the current tick."""

        inputs = EstimatorInputs()

        def collect(self):
            return self.inputs

    feed = _SimFeed()
    env.set_runtime_override(env.EVAC.name, "1")
    set_evacuation_handler(on_evacuate)
    ctl = PolicyController(
        feed=feed, estimator=GoodputEstimator(window_s=200.0)
    )
    overhead_evac = 0.0
    lead_times = []
    join_ms = []
    false_positives = 0
    missed = 0
    t = 0.0
    ei = 0
    active = None  # (victim_rank, onset)
    next_victim = 1000
    while t < TOTAL_S:
        signals = _healthy_signals(rng)
        if active is None and ei < len(onsets) and t >= onsets[ei]:
            active = (next_victim, onsets[ei])
            next_victim += 1
            ei += 1
        if active is not None:
            victim, onset = active
            frac = min(1.0, (t - onset) / RAMP_S)
            signals[victim] = RankSignals(
                health_score=frac,
                straggler_score=max(0.2, 1.0 - 0.8 * frac),
            )
        feed.inputs = EstimatorInputs(rank_signals=signals)
        ctl.tick(now=t)
        for r in list(evacuated_at):
            if evacuated_at[r] is True:
                evacuated_at[r] = t
                if active is not None and r == active[0]:
                    victim, onset = active
                    lead_times.append(onset + RAMP_S - t)
                    join_s = rng.uniform(*EVAC_JOIN_S)
                    join_ms.append(
                        (EVAC_CKPT_AHEAD_S + EVAC_PROMOTE_S + join_s)
                        * 1000.0
                    )
                    overhead_evac += (
                        EVAC_CKPT_AHEAD_S + EVAC_PROMOTE_S + join_s
                    )
                    ctl.estimator.rank_model.forget(victim)
                    active = None
                else:
                    false_positives += 1
        if active is not None and t >= active[1] + RAMP_S:
            # the model missed: the node died first — reactive episode
            missed += 1
            overhead_evac += (
                REACT_DETECT_S + REACT_RESTART_S + REACT_COLD_RESTORE_S
                + CKPT_INTERVAL_S / 2.0
            )
            ctl.estimator.rank_model.forget(active[0])
            active = None
        t += TICK_S
    set_evacuation_handler(None)
    env.clear_runtime_overrides()

    # -- react arm: every degradation runs to the hard fault
    overhead_react = len(onsets) * (
        REACT_DETECT_S + REACT_RESTART_S + REACT_COLD_RESTORE_S
        + CKPT_INTERVAL_S / 2.0
    )

    evac_goodput = max(0.0, (TOTAL_S - overhead_evac) / TOTAL_S)
    react_goodput = max(0.0, (TOTAL_S - overhead_react) / TOTAL_S)
    return {
        "seed": seed,
        "degradations": len(onsets),
        "evacuations": len(lead_times),
        "missed": missed,
        "false_positives": false_positives,
        "evac_goodput": round(evac_goodput, 4),
        "react_goodput": round(react_goodput, 4),
        "lead_time_s_mean": round(
            sum(lead_times) / len(lead_times), 1) if lead_times else None,
        "join_mttr_ms_mean": round(
            sum(join_ms) / len(join_ms), 1) if join_ms else None,
        "gain": round(evac_goodput / max(react_goodput, 1e-9), 3),
    }


def run(seed: int, trials: int = 3) -> dict:
    """Gate on the MEAN gain over derived schedules (not one lucky fault
    draw); any healthy-rank evacuation or missed ramp fails outright."""
    logging.getLogger("tpurx.policy.actuator").setLevel(logging.ERROR)
    logging.getLogger("tpurx.policy.evacuation").setLevel(logging.ERROR)
    results = [run_trial(seed + 211 * i) for i in range(max(1, trials))]
    mean_gain = sum(r["gain"] for r in results) / len(results)
    joins = [r["join_mttr_ms_mean"] for r in results if r["join_mttr_ms_mean"]]
    false_positives = sum(r["false_positives"] for r in results)
    missed = sum(r["missed"] for r in results)
    waived = (os.cpu_count() or 1) <= 1
    gain_ok = waived or mean_gain >= 1.1
    ok = bool(gain_ok and false_positives == 0 and missed == 0)
    return {
        "metric": "bench_evac",
        "seed": seed,
        "trials": len(results),
        "evac_goodput": round(
            sum(r["evac_goodput"] for r in results) / len(results), 4),
        "react_goodput": round(
            sum(r["react_goodput"] for r in results) / len(results), 4),
        "evac_trial_gains": [r["gain"] for r in results],
        "evac_false_positives": false_positives,
        "evac_missed": missed,
        "evac_join_mttr_ms": round(
            sum(joins) / len(joins), 1) if joins else None,
        "evac_trials": results,
        "evac_goodput_gain": round(mean_gain, 3),
        "evac_gate_waived": waived,
        "evac_ok": ok,
        "ok": ok,
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seed", type=int, default=0xE7AC)
    p.add_argument("--trials", type=int, default=3)
    args = p.parse_args()
    report = run(args.seed, args.trials)
    print(json.dumps(report))
    sys.exit(0 if report["ok"] else 1)


if __name__ == "__main__":
    main()
