"""Control-plane scale benchmark: rendezvous close latency, barrier fan-in,
and checkpoint-consensus cost at 64/128/256 simulated agents.

VERDICT round-1 weak #8 asked for measured behavior at 256+ clients plus a
fix for the O(world)-reads-per-check consensus; the consensus here is the
counter-based ``store_sync_fn`` (one ADD per rank + one read per poll).

Baseline to compare against: the reference reports 0.5 s rendezvous at 16k
ranks on its custom store host (``docs/.../usage_guide.rst:653-654``); this
harness measures the same protocol shape (join -> close -> result fan-out)
over this framework's KV store.

Run:  python benchmarks/bench_control_plane.py [--native] [--sizes 64,128,256]
Emits one JSON line per (size, metric).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, ".")

from tpu_resiliency.checkpointing.async_ckpt.core import store_sync_fn
from tpu_resiliency.fault_tolerance.rendezvous import (
    NodeDesc,
    RendezvousHost,
    RendezvousJoiner,
)
from tpu_resiliency.store import StoreClient, barrier


def _clients(port: int, n: int) -> list:
    return [StoreClient("127.0.0.1", port, timeout=120.0) for _ in range(n)]


def bench_rendezvous(port: int, n: int) -> dict:
    host_client = StoreClient("127.0.0.1", port, timeout=120.0)
    host = RendezvousHost(host_client, min_nodes=n, max_nodes=n, settle_time=0.1)
    host.bootstrap()
    round_num = host.open_round()
    clients = _clients(port, n)
    results: list = [None] * n
    errors: list = []

    def agent(i: int) -> None:
        desc = NodeDesc.create(node_id=f"bench-node-{i}", slots=1)
        joiner = RendezvousJoiner(clients[i], desc, open_poll_interval=0.05)
        try:
            results[i] = joiner.join(timeout=180.0)
        except Exception as exc:  # noqa: BLE001
            errors.append(repr(exc))

    threads = [threading.Thread(target=agent, args=(i,)) for i in range(n)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    closed = host.close_round_when_ready(timeout=180.0)
    close_latency = time.monotonic() - t0
    for t in threads:
        t.join(timeout=180)
    total_latency = time.monotonic() - t0
    for c in clients:
        c.close()
    host_client.close()
    assert not errors, errors[:3]
    assert closed == round_num
    worlds = {r.group_world_size for r in results if r is not None}
    assert worlds == {n}, worlds
    return {
        "round_close_s": round(close_latency, 4),
        "result_fanout_s": round(total_latency, 4),
    }


def bench_barrier(port: int, n: int) -> dict:
    clients = _clients(port, n)
    t0 = time.monotonic()
    threads = [
        threading.Thread(
            target=barrier,
            args=(clients[i], f"bench-{n}", n),
            kwargs={"timeout": 180.0, "poll_interval": 0.02},
        )
        for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    elapsed = time.monotonic() - t0
    for c in clients:
        c.close()
    return {"barrier_fanin_s": round(elapsed, 4)}


def bench_consensus(port: int, n: int, calls: int = 4) -> dict:
    clients = _clients(port, n)
    syncs = [
        store_sync_fn(clients[i], rank=i, world_size=n, namespace=f"bench{n}")
        for i in range(n)
    ]
    t0 = time.monotonic()
    for idx in range(calls):
        def publish(i: int) -> None:
            syncs[i](idx, True)

        threads = [threading.Thread(target=publish, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        # rank 0 polls to global completion: counter scheme = 1 read/poll
        while not syncs[0](idx, True):
            time.sleep(0.001)
    elapsed = time.monotonic() - t0
    for c in clients:
        c.close()
    return {
        "consensus_total_s": round(elapsed, 4),
        "consensus_per_call_s": round(elapsed / calls, 4),
    }


# -- 10k-rank sweep: affinity + one-RTT rounds vs the PR 6 protocol ----------


def _spawn_fleet(shards: int, native: bool):
    """K shard servers, each its own OS process (real parallelism either
    way: the native wrapper runs the C++ binary, the python path uses
    ``spawn_shard_subprocess``).  Returns (endpoints, stop_fn)."""
    from tpu_resiliency.store.sharding import free_port, spawn_shard_subprocess

    if native:
        from tpu_resiliency.store.native import NativeStoreServer

        servers = [
            NativeStoreServer(host="127.0.0.1", port=0).start()
            for _ in range(shards)
        ]
        endpoints = [f"127.0.0.1:{s.port}" for s in servers]

        def stop():
            for s in servers:
                s.stop()
    else:
        from tpu_resiliency.utils.env import disarm_platform_sitecustomize

        env = {"JAX_PLATFORMS": "cpu"}
        disarm_platform_sitecustomize(env)
        procs, endpoints = [], []
        for _ in range(shards):
            port = free_port()
            procs.append(spawn_shard_subprocess(port, env=env))
            endpoints.append(f"127.0.0.1:{port}")

        def stop():
            for p in procs:
                p.kill()
    return endpoints, stop


def _run_pool(worker, ranks: int, workers: int) -> None:
    """Drive ``ranks`` simulated clients from a bounded thread pool: each
    thread registers its slice sequentially, so 10k ranks costs 10k ops
    over ~32 sockets, not 10k threads."""
    per, extra = divmod(ranks, workers)
    threads = [
        threading.Thread(
            target=worker, args=(tid, per + (1 if tid < extra else 0)),
            daemon=True,
        )
        for tid in range(workers)
    ]
    for t in threads:
        t.start()
    return threads


def rdzv_close_fast_ms(endpoints, ranks: int, workers: int = 32) -> float:
    """The shipped path: affinity-routed one-RTT ADD_SET joins against the
    real host (WAIT_GE arrival fence + batched desc reads)."""
    from tpu_resiliency.fault_tolerance.rendezvous import (
        _desc_json_with_arrival_slot,
        k_join_count,
        k_node,
    )
    from tpu_resiliency.store.sharding import ShardedStoreClient

    sweeper = ShardedStoreClient(endpoints, timeout=120.0)
    for k in sweeper.list_keys("rdzv/"):
        sweeper.delete(k)
    sweeper.close()
    host_client = ShardedStoreClient(endpoints, timeout=600.0)
    host = RendezvousHost(
        host_client, min_nodes=ranks, max_nodes=ranks, settle_time=0.2
    )
    host.bootstrap()
    n = host.open_round()
    base = NodeDesc.create(node_id="sweep", slots=1)

    def worker(tid: int, count: int) -> None:
        c = ShardedStoreClient(endpoints, timeout=600.0)
        group = c.affinity(f"rdzv/{n}")  # single-shard handle (asserted)
        try:
            for i in range(count):
                nid = f"n-{tid}-{i}"
                group.add_set(
                    k_join_count(n), 1, k_node(n, nid),
                    _desc_json_with_arrival_slot(
                        dataclasses.replace(base, node_id=nid)
                    ),
                )
        finally:
            c.close()

    t0 = time.monotonic()
    threads = _run_pool(worker, ranks, workers)
    host.close_round_when_ready(timeout=600.0)
    close_ms = (time.monotonic() - t0) * 1e3
    for t in threads:
        t.join(timeout=60)
    host_client.close()
    return close_ms


def rdzv_close_pr6_ms(endpoints, ranks: int, workers: int = 32) -> float:
    """The pre-affinity protocol at equal shard count: three-RTT joins
    (ADD counter, SET node record, SET exact-count marker), per-key host
    desc reads, count-marker arrival waits, per-key routing (affinity
    off).  The emulation is CHARITABLE to the old path — each desc is
    read once (the cache the old host already had) and the per-wake
    ``list_keys`` cost is kept, so a measured win understates the real
    one."""
    from tpu_resiliency.fault_tolerance.rendezvous import (
        assign_group_ranks,
        k_closed,
        k_count,
        k_done,
        k_join_count,
        k_node,
        k_open,
        k_result,
    )
    from tpu_resiliency.store.client import StoreTimeout
    from tpu_resiliency.store.sharding import ShardedStoreClient

    c0 = ShardedStoreClient(endpoints, timeout=600.0, affinity=False)
    for k in c0.list_keys("rdzv/"):
        c0.delete(k)
    n = 0
    c0.set(k_open(n), b"1")
    base = NodeDesc.create(node_id="sweep", slots=1)

    def worker(tid: int, count: int) -> None:
        c = ShardedStoreClient(endpoints, timeout=600.0, affinity=False)
        try:
            for i in range(count):
                nid = f"p-{tid}-{i}"
                arrival = c.add(k_join_count(n), 1)
                c.set(
                    k_node(n, nid),
                    dataclasses.replace(
                        base, node_id=nid, arrival=arrival
                    ).to_json(),
                )
                c.set(k_count(n, arrival), b"1")
        finally:
            c.close()

    t0 = time.monotonic()
    threads = _run_pool(worker, ranks, workers)
    desc_cache: dict = {}
    while True:
        count = int(c0.try_get(k_join_count(n)) or b"0")
        for key in c0.list_keys(f"rdzv/{n}/node/"):
            if key not in desc_cache:
                raw = c0.try_get(key)  # PER-KEY read: the serial O(N) cost
                if raw is not None:
                    desc_cache[key] = NodeDesc.from_json(raw)
        if len(desc_cache) >= ranks:
            break
        try:
            c0.wait([k_count(n, count + 1)], timeout=2.0)
        except StoreTimeout:
            pass
    c0.set(k_closed(n), b"1")
    nodes = list(desc_cache.values())
    assignment = assign_group_ranks(nodes, ranks, ranks)
    participants = sorted(
        (nid for nid, a in assignment.items() if a["group_rank"] is not None),
        key=lambda nid: assignment[nid]["group_rank"],
    )
    c0.set(k_result(n), json.dumps({
        "assignment": assignment,
        "participants": participants,
        "slots": {d.node_id: d.slots for d in nodes},
        "cycle": 0,
    }))
    c0.set(k_done(n), b"1")
    close_ms = (time.monotonic() - t0) * 1e3
    for t in threads:
        t.join(timeout=60)
    c0.close()
    return close_ms


def measure_protocol_rtts(port: int) -> dict:
    """Count the MUTATION round trips one barrier arrival and one
    rendezvous registration actually send — the 1-RTT claim, measured."""
    from tpu_resiliency.fault_tolerance.rendezvous import k_join_count, k_node
    from tpu_resiliency.store.protocol import Op
    from tpu_resiliency.store import reentrant_barrier

    muts = {
        Op.SET, Op.ADD, Op.APPEND, Op.COMPARE_SET, Op.DELETE, Op.MULTI_SET,
        Op.APPEND_CHECK, Op.ADD_SET,
    }

    class Counting(StoreClient):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self.ops = []

        def _roundtrip(self, op, args, io_timeout):
            self.ops.append(Op(op))
            return super()._roundtrip(op, args, io_timeout)

    c = Counting("127.0.0.1", port, timeout=30.0)
    reentrant_barrier(c, "rtt-probe", 0, 1, timeout=10.0)
    barrier_rtts = sum(1 for op in c.ops if op in muts)
    c.ops.clear()
    c.add_set(k_join_count(900), 1, k_node(900, "probe"), b"{}")
    join_rtts = sum(1 for op in c.ops if op in muts)
    for key in ("barrier/rtt-probe/arrivals", "barrier/rtt-probe/done",
                k_join_count(900), k_node(900, "probe")):
        c.delete(key)
    c.close()
    return {"barrier_arrival_rtts": barrier_rtts, "rdzv_join_rtts": join_rtts}


def measure_promote_ms() -> float:
    """SIGKILL a shard and clock the full recovery: journal-restored spare
    on a FRESH port + CAS'd epoch bump on the published map."""
    from tpu_resiliency.store import promote_spare
    from tpu_resiliency.store.sharding import (
        SHARD_MAP_KEY,
        ShardMap,
        free_port,
        spawn_shard_subprocess,
    )
    from tpu_resiliency.utils.env import disarm_platform_sitecustomize

    env = {"JAX_PLATFORMS": "cpu"}
    disarm_platform_sitecustomize(env)
    with tempfile.TemporaryDirectory(prefix="tpurx-promote-") as tmp:
        ports = [free_port(), free_port()]
        spare_port = free_port()
        journals = [os.path.join(tmp, f"j{i}") for i in range(2)]
        procs = [
            spawn_shard_subprocess(p, journal=j, env=env)
            for p, j in zip(ports, journals)
        ]
        spare = None
        try:
            seed = StoreClient("127.0.0.1", ports[0], timeout=10.0)
            seed.set(SHARD_MAP_KEY, ShardMap(
                [f"127.0.0.1:{p}" for p in ports],
                spares=[f"127.0.0.1:{spare_port}"],
            ).to_json())
            # victim carries state so the replay is not measuring an
            # empty journal
            direct = StoreClient("127.0.0.1", ports[1], timeout=10.0)
            for i in range(512):
                direct.set(f"state/{i}", b"x" * 64)
            direct.close()
            procs[1].kill()
            procs[1].wait(timeout=10)
            t0 = time.monotonic()
            spare = spawn_shard_subprocess(
                spare_port, journal=journals[1], env=env
            )
            promote_spare(seed, 1, f"127.0.0.1:{spare_port}")
            promote_ms = (time.monotonic() - t0) * 1e3
            seed.close()
            return promote_ms
        finally:
            for p in procs:
                p.kill()
            if spare is not None:
                spare.kill()


def rendezvous_10k_sweep(
    shards: int = 4,
    ranks: int = 10000,
    native: bool = False,
    workers: int = 32,
) -> dict:
    """The acceptance sweep: fast vs PR 6 rendezvous close at ``ranks``
    simulated clients over an equal shard fleet, plus the measured per-op
    RTT counts and the spare-promotion latency.  Gate: >=2x close speedup
    (waived on a 1-core host, house style)."""
    endpoints, stop = _spawn_fleet(shards, native)
    try:
        fast_ms = rdzv_close_fast_ms(endpoints, ranks, workers)
        pr6_ms = rdzv_close_pr6_ms(endpoints, ranks, workers)
        rtts = measure_protocol_rtts(int(endpoints[0].rsplit(":", 1)[1]))
    finally:
        stop()
    speedup = pr6_ms / max(1e-9, fast_ms)
    waived = (os.cpu_count() or 1) < 2 and speedup < 2.0
    out = {
        "rdzv10k_ranks": ranks,
        "rdzv10k_shards": shards,
        "rdzv_close_10k_ms": round(fast_ms, 1),
        "rdzv_close_10k_pr6_ms": round(pr6_ms, 1),
        "rdzv10k_speedup": round(speedup, 2),
        "rdzv10k_ok": bool(speedup >= 2.0 or waived),
    }
    if waived:
        out["rdzv10k_gate_waived"] = "1-core host"
    out.update(rtts)
    out["store_promote_ms"] = round(measure_promote_ms(), 1)
    return out


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--sizes", default="64,128,256")
    p.add_argument("--native", action="store_true")
    p.add_argument(
        "--shards", type=int, default=0,
        help="run the 10k-rank sweep over this many shards instead of the "
             "--sizes ladder",
    )
    p.add_argument("--ranks", type=int, default=10000)
    p.add_argument("--workers", type=int, default=32)
    args = p.parse_args()

    if args.shards > 0:
        print(json.dumps(rendezvous_10k_sweep(
            shards=args.shards, ranks=args.ranks, native=args.native,
            workers=args.workers,
        )), flush=True)
        return

    if args.native:
        from tpu_resiliency.store.native import NativeStoreServer

        server = NativeStoreServer(host="127.0.0.1", port=0).start()
        kind = "native-cpp"
    else:
        from tpu_resiliency.store import StoreServer

        server = StoreServer(host="127.0.0.1", port=0).start_in_thread()
        kind = "python-asyncio"

    try:
        for n in [int(s) for s in args.sizes.split(",")]:
            row = {"store": kind, "agents": n}
            row.update(bench_rendezvous(server.port, n))
            row.update(bench_barrier(server.port, n))
            row.update(bench_consensus(server.port, n))
            print(json.dumps(row), flush=True)
    finally:
        server.stop()


if __name__ == "__main__":
    main()
