"""Control-plane scale benchmark: rendezvous close latency, barrier fan-in,
and checkpoint-consensus cost at 64/128/256 simulated agents.

VERDICT round-1 weak #8 asked for measured behavior at 256+ clients plus a
fix for the O(world)-reads-per-check consensus; the consensus here is the
counter-based ``store_sync_fn`` (one ADD per rank + one read per poll).

Baseline to compare against: the reference reports 0.5 s rendezvous at 16k
ranks on its custom store host (``docs/.../usage_guide.rst:653-654``); this
harness measures the same protocol shape (join -> close -> result fan-out)
over this framework's KV store.

Run:  python benchmarks/bench_control_plane.py [--native] [--sizes 64,128,256]
Emits one JSON line per (size, metric).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

sys.path.insert(0, ".")

from tpu_resiliency.checkpointing.async_ckpt.core import store_sync_fn
from tpu_resiliency.fault_tolerance.rendezvous import (
    NodeDesc,
    RendezvousHost,
    RendezvousJoiner,
)
from tpu_resiliency.store import StoreClient, barrier


def _clients(port: int, n: int) -> list:
    return [StoreClient("127.0.0.1", port, timeout=120.0) for _ in range(n)]


def bench_rendezvous(port: int, n: int) -> dict:
    host_client = StoreClient("127.0.0.1", port, timeout=120.0)
    host = RendezvousHost(host_client, min_nodes=n, max_nodes=n, settle_time=0.1)
    host.bootstrap()
    round_num = host.open_round()
    clients = _clients(port, n)
    results: list = [None] * n
    errors: list = []

    def agent(i: int) -> None:
        desc = NodeDesc.create(node_id=f"bench-node-{i}", slots=1)
        joiner = RendezvousJoiner(clients[i], desc, open_poll_interval=0.05)
        try:
            results[i] = joiner.join(timeout=180.0)
        except Exception as exc:  # noqa: BLE001
            errors.append(repr(exc))

    threads = [threading.Thread(target=agent, args=(i,)) for i in range(n)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    closed = host.close_round_when_ready(timeout=180.0)
    close_latency = time.monotonic() - t0
    for t in threads:
        t.join(timeout=180)
    total_latency = time.monotonic() - t0
    for c in clients:
        c.close()
    host_client.close()
    assert not errors, errors[:3]
    assert closed == round_num
    worlds = {r.group_world_size for r in results if r is not None}
    assert worlds == {n}, worlds
    return {
        "round_close_s": round(close_latency, 4),
        "result_fanout_s": round(total_latency, 4),
    }


def bench_barrier(port: int, n: int) -> dict:
    clients = _clients(port, n)
    t0 = time.monotonic()
    threads = [
        threading.Thread(
            target=barrier,
            args=(clients[i], f"bench-{n}", n),
            kwargs={"timeout": 180.0, "poll_interval": 0.02},
        )
        for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    elapsed = time.monotonic() - t0
    for c in clients:
        c.close()
    return {"barrier_fanin_s": round(elapsed, 4)}


def bench_consensus(port: int, n: int, calls: int = 4) -> dict:
    clients = _clients(port, n)
    syncs = [
        store_sync_fn(clients[i], rank=i, world_size=n, namespace=f"bench{n}")
        for i in range(n)
    ]
    t0 = time.monotonic()
    for idx in range(calls):
        def publish(i: int) -> None:
            syncs[i](idx, True)

        threads = [threading.Thread(target=publish, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        # rank 0 polls to global completion: counter scheme = 1 read/poll
        while not syncs[0](idx, True):
            time.sleep(0.001)
    elapsed = time.monotonic() - t0
    for c in clients:
        c.close()
    return {
        "consensus_total_s": round(elapsed, 4),
        "consensus_per_call_s": round(elapsed / calls, 4),
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--sizes", default="64,128,256")
    p.add_argument("--native", action="store_true")
    args = p.parse_args()

    if args.native:
        from tpu_resiliency.store.native import NativeStoreServer

        server = NativeStoreServer(host="127.0.0.1", port=0).start()
        kind = "native-cpp"
    else:
        from tpu_resiliency.store import StoreServer

        server = StoreServer(host="127.0.0.1", port=0).start_in_thread()
        kind = "python-asyncio"

    try:
        for n in [int(s) for s in args.sizes.split(",")]:
            row = {"store": kind, "agents": n}
            row.update(bench_rendezvous(server.port, n))
            row.update(bench_barrier(server.port, n))
            row.update(bench_consensus(server.port, n))
            print(json.dumps(row), flush=True)
    finally:
        server.stop()


if __name__ == "__main__":
    main()
