"""Secondary benchmark: async-checkpoint step-time overhead %.

Driver metric #2 (BASELINE.json), target <5%.  NOTE: in this sandbox the TPU
is tunneled (axon relay) and D2H runs at ~25MB/s (measured: 233MB optimizer
state stages in ~10s vs ~25ms on a real v5e host), so the absolute overhead
number here measures the tunnel, not the framework — which is why the
headline ``bench.py`` reports hang-detection latency instead.  On real
hardware this script is the one to watch.

Prints ONE JSON line: {"metric": "async_ckpt_step_overhead_pct", ...}.
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(steps: int = 200, save_every: int = 100) -> None:
    import jax

    from tpu_resiliency.checkpointing import AsyncCheckpointer
    from tpu_resiliency.models.transformer import (
        TransformerConfig,
        init_opt_state,
        init_params,
        make_batch,
        make_train_step,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = TransformerConfig(
        vocab=8192,
        d_model=512 if on_tpu else 128,
        n_heads=8 if on_tpu else 4,
        n_layers=6 if on_tpu else 2,
        d_ff=2048 if on_tpu else 256,
        max_seq=512 if on_tpu else 64,
    )
    params = init_params(cfg)
    opt = init_opt_state(params)
    batch = make_batch(cfg, 16 if on_tpu else 4, cfg.max_seq)
    step = make_train_step(cfg)
    params, opt, loss = step(params, opt, batch)
    jax.block_until_ready(loss)

    def run(n, ckpt=None, ckpt_dir=None):
        nonlocal params, opt
        t0 = time.perf_counter()
        for i in range(n):
            params, opt, loss = step(params, opt, batch)
            if ckpt is not None:
                if i % save_every == 0:
                    ckpt.async_save(
                        {"params": params, "opt": opt},
                        os.path.join(ckpt_dir, f"step_{i}"),
                        extra_metadata={"iteration": i},
                    )
                ckpt.maybe_finalize()
        jax.block_until_ready(loss)
        return (time.perf_counter() - t0) / n

    base_a = run(steps)
    tmp = tempfile.mkdtemp(prefix="tpurx-bench-")
    ckpt = AsyncCheckpointer()
    try:
        ckpt_t = run(steps, ckpt=ckpt, ckpt_dir=tmp)
        base_b = run(steps)
        ckpt.finalize_all()
    finally:
        ckpt.close()
        shutil.rmtree(tmp, ignore_errors=True)

    base = min(base_a, base_b)
    overhead_pct = max(0.0, (ckpt_t / base - 1.0) * 100.0)
    print(
        json.dumps(
            {
                "metric": "async_ckpt_step_overhead_pct",
                "value": round(overhead_pct, 3),
                "unit": "%",
                "vs_baseline": round(overhead_pct / 5.0, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
