"""Secondary benchmark: async-checkpoint step-time overhead %.

Driver metric #2 (BASELINE.json), target <5%.  Thin wrapper over the
paired-stall measurement in the repo-root ``bench.py`` (which emits this
number alongside the detection metric in the driver-captured line): the
per-save costs (snapshot-dispatch call + post-save drain stall) are measured
against ADJACENT baseline step groups — robust to the tunneled relay's
minute-scale throughput drift — then amortized over a save cadence sized to
the measured D2H bandwidth.

Prints ONE JSON line: {"metric": "async_ckpt_step_overhead_pct", ...}.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    from bench import bench_async_ckpt

    overhead_pct, d2h_mbps, state_bytes, save_every = bench_async_ckpt()
    print(
        json.dumps(
            {
                "metric": "async_ckpt_step_overhead_pct",
                "value": round(overhead_pct, 3),
                "unit": "%",
                "vs_baseline": round(overhead_pct / 5.0, 3),
                "d2h_mbps": round(d2h_mbps, 1),
                "state_mb": round(state_bytes / 1e6, 1),
                "save_every": save_every,
            }
        )
    )


if __name__ == "__main__":
    main()
