"""Measured in-process mesh-shrink: can jax.distributed re-init at N-1?

The open research item behind the abort ladder's ``ShrinkMeshStage``
(SURVEY §7(a), VERDICT r5 'do this' #4): the reference recovers a wedged
collective *in the process* by aborting NCCL communicators; the JAX analog
would be tearing down the ``jax.distributed`` client and re-initializing
at the surviving world size without a respawn.  Whether that works is a
per-JAX-version property of the runtime, not something prose can settle —
so this script MEASURES it:

1. spawn N worker processes, ``jax.distributed.initialize`` at N
   (coordinator on worker 0), prove a cross-process collective;
2. SIGKILL the highest worker (never the coordinator);
3. survivors attempt the in-process shrink, each step timed and deadlined
   exactly like the ladder stage: ``jax.distributed.shutdown()`` →
   ``jax.clear_caches()`` (+ ``clear_backends`` where the version has it) →
   ``jax.distributed.initialize`` at N-1 on a FRESH coordinator port →
   prove a collective at the new world size.

Output: one JSON line per run —
``{"metric": "mesh_shrink", "jax_version": ..., "phases": {...},
"shrink_ok": bool, "verdict": "..."}`` — the per-JAX-version row for the
result matrix in ``docs/inprocess.md``.  A hang in any step is bounded by
``--deadline`` (a wedged runtime blocking ``shutdown()`` in C++ is itself a
finding: it is why the ladder stage carries a deadline and falls through
to the monitor-kill backstop).

Run:    JAX_PLATFORMS=cpu python benchmarks/mesh_shrink_experiment.py
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpu_resiliency.utils.env import disarm_platform_sitecustomize  # noqa: E402

WORKER = r"""
import json, os, sys, threading, time

sys.path.insert(0, os.environ["TPURX_REPO"])

N = int(os.environ["MS_N"])
PID = int(os.environ["MS_PID"])
COORD = os.environ["MS_COORD"]
COORD2 = os.environ["MS_COORD2"]
FLAG_DIR = os.environ["MS_FLAGS"]
DEADLINE = float(os.environ.get("MS_DEADLINE", "30"))


def emit(phase, ok, ms, detail=""):
    print(json.dumps({"pid": PID, "phase": phase, "ok": ok,
                      "ms": round(ms, 1), "detail": str(detail)[:300]}),
          flush=True)


def timed(phase, fn):
    '''Run fn under the stage-style deadline; a hang records timed_out.'''
    box = {}

    def body():
        try:
            box["ret"] = fn()
        except BaseException as exc:
            box["exc"] = exc

    t0 = time.monotonic()
    th = threading.Thread(target=body, daemon=True)
    th.start()
    th.join(timeout=DEADLINE)
    ms = (time.monotonic() - t0) * 1e3
    if th.is_alive():
        emit(phase, False, ms, f"timed_out at {DEADLINE}s deadline")
        return False, None
    if "exc" in box:
        emit(phase, False, ms, repr(box["exc"]))
        return False, None
    emit(phase, True, ms, box.get("ret", ""))
    return True, box.get("ret")


def wait_flag(name, timeout=120.0):
    path = os.path.join(FLAG_DIR, name)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.05)
    return False


def set_flag(name):
    open(os.path.join(FLAG_DIR, name), "w").close()


import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")


def init_at(coord, n, pid):
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=n, process_id=pid)
    return f"procs={jax.process_count()}"


def prove_coordination(n, tag):
    '''Cross-process proof via the coordination service (works on every
    backend; the thing the shrink must re-establish).'''
    from jax._src import distributed

    client = distributed.global_state.client
    client.key_value_set(f"proof/{tag}/{PID}", str(PID))
    client.wait_at_barrier(f"barrier_{tag}", 10_000)
    for p in range(n):
        got = client.blocking_key_value_get(f"proof/{tag}/{p}", 5_000)
        assert got == str(p), f"kv mismatch for {p}: {got!r}"
    return f"kv_barrier_ok n={n}"


def prove_collective(n, tag):
    '''Device-collective proof — records the backend's own capability
    (CPU multiprocess collectives are unimplemented; TPU/GPU run them).'''
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    val = multihost_utils.process_allgather(jnp.float32(PID + 1))
    return f"allgather_sum={float(val.sum())}"


ok, _ = timed("init_n", lambda: init_at(COORD, N, PID))
if ok:
    ok, _ = timed("coordination_n", lambda: prove_coordination(N, "n"))
    timed("collective_n", lambda: prove_collective(N, "n"))  # informational
set_flag(f"ready_{PID}")
if PID == N - 1:
    if os.environ.get("MS_VICTIM") == "clean":
        # clean leave: the victim detaches properly — isolates "can this
        # jax re-init in-process at all" from "does a dead peer wedge it"
        timed("victim_shutdown", lambda: jax.distributed.shutdown())
        emit("victim_left", True, 0.0, "clean shutdown")
        sys.exit(0)
    time.sleep(3600)  # park until the supervisor SIGKILLs us
if not wait_flag("shrink"):
    emit("wait_shrink", False, 0.0, "no shrink flag")
    sys.exit(1)

# --- in-process shrink attempt (the ShrinkMeshStage body, measured) ---
ok, _ = timed("shutdown", lambda: jax.distributed.shutdown())
shrunk = False
if ok:
    def clear():
        jax.clear_caches()
        cleared = "caches"
        try:
            import jax.extend.backend as jeb  # lazy submodule: import, not attr

            jeb.clear_backends()
            cleared += "+backends"
        except Exception as exc:
            cleared += f" (clear_backends unavailable: {type(exc).__name__})"
        from jax._src import xla_bridge as xb

        cleared += f" initialized={xb.backends_are_initialized()}"
        return cleared

    ok, _ = timed("clear", clear)
    # survivors keep their ORIGINAL process ids sans the victim, compacted
    new_pid = PID
    ok2, _ = timed("reinit_n1", lambda: init_at(COORD2, N - 1, new_pid))
    if ok2:
        shrunk, _ = timed(
            "coordination_n1", lambda: prove_coordination(N - 1, "n1")
        )
        timed("collective_n1", lambda: prove_collective(N - 1, "n1"))
emit("shrink_result", bool(shrunk), 0.0,
     "in-process re-init at N-1 succeeded" if shrunk else
     "in-process re-init at N-1 failed")
sys.exit(0 if shrunk else 3)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_experiment(n: int, deadline: float, budget: float,
                   victim_mode: str = "kill") -> dict:
    import jax

    flags = tempfile.mkdtemp(prefix="tpurx-meshshrink-")
    coord = f"127.0.0.1:{_free_port()}"
    coord2 = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    disarm_platform_sitecustomize(env)
    env.update({
        "TPURX_REPO": REPO,
        "MS_N": str(n),
        "MS_COORD": coord,
        "MS_COORD2": coord2,
        "MS_FLAGS": flags,
        "MS_DEADLINE": str(deadline),
        "MS_VICTIM": victim_mode,
        "JAX_PLATFORMS": "cpu",
    })
    workers = []
    for pid in range(n):
        wenv = dict(env)
        wenv["MS_PID"] = str(pid)
        workers.append(subprocess.Popen(
            [sys.executable, "-u", "-c", WORKER], env=wenv,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            start_new_session=True,
        ))

    outputs = {i: [] for i in range(n)}

    def drain(i, proc):
        for line in proc.stdout:
            outputs[i].append(line)

    readers = [threading.Thread(target=drain, args=(i, p), daemon=True)
               for i, p in enumerate(workers)]
    for r in readers:
        r.start()

    t0 = time.monotonic()
    # wait for every worker's ready flag, then kill the victim
    while time.monotonic() - t0 < budget:
        if all(os.path.exists(os.path.join(flags, f"ready_{i}"))
               for i in range(n)):
            break
        if any(p.poll() is not None for p in workers[:-1]):
            break
        time.sleep(0.1)
    victim = workers[-1]
    if victim_mode == "kill":
        try:
            os.killpg(victim.pid, signal.SIGKILL)
        except OSError:
            victim.kill()
    else:
        try:  # clean mode: the victim shuts itself down and exits
            victim.wait(timeout=max(1.0, deadline + 10.0))
        except subprocess.TimeoutExpired:
            os.killpg(victim.pid, signal.SIGKILL)
    open(os.path.join(flags, "shrink"), "w").close()

    deadline_t = t0 + budget
    for i, p in enumerate(workers[:-1]):
        try:
            p.wait(timeout=max(1.0, deadline_t - time.monotonic()))
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except OSError:
                p.kill()
    victim.wait(timeout=10)
    for r in readers:
        r.join(timeout=5)

    phases: dict = {}
    for i in range(n):
        for raw in outputs[i]:
            raw = raw.strip()
            if not raw.startswith("{"):
                continue
            try:
                ev = json.loads(raw)
            except json.JSONDecodeError:
                continue
            key = ev["phase"]
            cur = phases.setdefault(key, {"ok": True, "ms": [], "detail": ""})
            cur["ok"] = cur["ok"] and bool(ev["ok"])
            cur["ms"].append(ev["ms"])
            if not ev["ok"] and not cur["detail"]:
                cur["detail"] = ev.get("detail", "")
    for v in phases.values():
        v["ms"] = round(max(v["ms"]), 1) if v["ms"] else None

    survivors_rc = [p.returncode for p in workers[:-1]]
    shrink_ok = bool(phases.get("shrink_result", {}).get("ok")) and all(
        rc == 0 for rc in survivors_rc
    )
    if shrink_ok:
        verdict = (
            f"in-process shrink WORKS on jax {jax.__version__} "
            f"({victim_mode} victim): survivors re-initialized at N-1 and "
            "re-established cross-process coordination without a respawn"
        )
    else:
        blocking = next(
            (f"{k}: {v['detail']}" for k, v in phases.items()
             if not v["ok"] and v["detail"]),
            "no failing phase captured",
        )
        verdict = (
            f"in-process shrink FAILS on jax {jax.__version__} "
            f"({victim_mode} victim) — {blocking}; ShrinkMeshStage must keep "
            "its deadline + monitor-kill fallback"
        )
    return {
        "metric": "mesh_shrink",
        "jax_version": jax.__version__,
        "n": n,
        "victim_mode": victim_mode,
        "phases": phases,
        "survivor_rcs": survivors_rc,
        "shrink_ok": shrink_ok,
        "verdict": verdict,
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=3,
                   help="initial world size (victim = highest pid)")
    p.add_argument("--deadline", type=float, default=30.0,
                   help="per-step deadline inside each worker (stage analog)")
    p.add_argument("--budget", type=float, default=240.0,
                   help="whole-experiment wall budget")
    p.add_argument("--victim", choices=("kill", "clean", "both"),
                   default="both",
                   help="SIGKILL the victim (failure reality), let it leave "
                        "cleanly (version capability), or measure both")
    args = p.parse_args()
    modes = ["kill", "clean"] if args.victim == "both" else [args.victim]
    results = [
        run_experiment(args.n, args.deadline, args.budget, m) for m in modes
    ]
    for r in results:
        print(json.dumps(r))
    sys.exit(0 if all(r["shrink_ok"] for r in results) else 3)


if __name__ == "__main__":
    main()
