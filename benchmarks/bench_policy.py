"""Adaptive-vs-best-fixed goodput under a fault-regime shift.

A seeded discrete-event simulation of a checkpointed training run — work
accrues between saves, each save costs C seconds, each fault destroys
the uncommitted tail and costs a recovery — driving the REAL policy
components end to end:

- the adaptive arm feeds cumulative fault counts into
  :class:`tpu_resiliency.policy.GoodputEstimator` (windowed MTBF, EWMA'd
  C, Young/Daly ``tau_opt``) and applies cadence through the real
  :class:`Actuator` (clamp + hysteresis + runtime knob override), read
  back per save decision exactly as ``SaveScheduler.interval_s`` would;
- restart-rung choice goes through the real :class:`RungLedger`: hangs
  always escalate past in-process and mesh-shrink, so the fixed arm pays
  the full ladder walk on every hang while the adaptive arm's ledger
  learns the terminal rung after a few episodes.

The exception-fault schedule has a regime step (noisy then quiet); no
single fixed cadence serves both phases, and no static rung start serves
a class that always escalates.  The fixed arm sweeps a cadence grid and
reports its BEST goodput; the gate asserts the closed loop beats that
best fixed knob by >= 1.1x (``policy_goodput_gain``).  The sim is
deterministic: same seed, same schedule, same verdict on every host.

Emits one JSON line:  python benchmarks/bench_policy.py [--seed N]
"""

from __future__ import annotations

import argparse
import json
import logging
import math
import os
import random
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpu_resiliency.policy import (  # noqa: E402
    Actuator, EstimatorInputs, GoodputEstimator, RungLedger,
)
from tpu_resiliency.utils import env  # noqa: E402

# exception regime: a noisy phase (MTBF comparable to the save cost — the
# goodput peak is sharp and sits at a short cadence) followed by a quiet
# one (overhead dominates — the peak sits far to the right)
PHASE1_MTBF_S = 25.0
PHASE2_MTBF_S = 300.0
PHASE1_LEN_S = 2000.0
TOTAL_S = 6000.0
CKPT_COST_S = 8.0

# hangs arrive at a steady slow rate in BOTH phases; their in-process and
# mesh-shrink rungs never release (a wedged collective needs the full
# in-job restart), so a static ladder pays every rung's cost each time
HANG_MTBF_S = 350.0
RUNG_COST_S = {"in_process": 20.0, "mesh_shrink": 45.0, "in_job": 60.0}
RUNG_ORDER = ("in_process", "mesh_shrink", "in_job")
EXC_RECOVERY_S = 5.0  # exceptions: the in-process ring absorbs them

FIXED_GRID_S = (10.0, 14.0, 20.0, 28.0, 40.0, 57.0, 80.0, 120.0, 200.0)


def draw_fault_times(seed: int) -> list:
    """Merged, sorted ``(t, kind)`` stream: exponential interarrivals per
    class, phase-dependent for exceptions.  Deterministic in ``seed``."""
    rng = random.Random(seed)
    events = []
    t = 0.0
    while t < TOTAL_S:
        mtbf = PHASE1_MTBF_S if t < PHASE1_LEN_S else PHASE2_MTBF_S
        t += rng.expovariate(1.0 / mtbf)
        if t < TOTAL_S:
            events.append((t, "exception"))
    t = 0.0
    while t < TOTAL_S:
        t += rng.expovariate(1.0 / HANG_MTBF_S)
        if t < TOTAL_S:
            events.append((t, "hang"))
    events.sort()
    return events


def walk_ladder(start_rung: str) -> float:
    """Recovery cost of a hang when the ladder starts at ``start_rung``:
    every rung below in_job fails (and bills its cost) before in_job
    releases.  Returns (total_cost, [(rung, success, cost), ...])."""
    total = 0.0
    episodes = []
    for rung in RUNG_ORDER[RUNG_ORDER.index(start_rung):]:
        cost = RUNG_COST_S[rung]
        total += cost
        episodes.append((rung, rung == "in_job", cost))
    return total, episodes


class FixedPolicy:
    """One fixed cadence, the static default ladder start."""

    def __init__(self, interval_s: float):
        self.interval_s = interval_s

    def next_interval(self, now: float) -> float:
        return self.interval_s

    def recover(self, now: float, kind: str) -> float:
        if kind == "exception":
            return EXC_RECOVERY_S
        cost, _ = walk_ladder("in_process")
        return cost

    def on_save(self, now: float, cost_s: float) -> None:
        pass


class AdaptivePolicy:
    """The real estimator + actuator + rung ledger closing the loop over
    sim time.  The sim observes what the live stack would: cumulative
    fault counts per class, the measured save cost, per-rung episode
    outcomes.  Cadence comes back out through the runtime knob override —
    the same path ``SaveScheduler.interval_s`` takes in a trainer."""

    def __init__(self, window_s: float, default_interval_s: float):
        self.est = GoodputEstimator(window_s=window_s)
        self.act = Actuator()
        self.led = RungLedger()
        self.default_interval_s = default_interval_s
        self.counts = {"exception": 0, "hang": 0}
        self.ckpt_cost_s = None
        self.recovery_cost_s = None
        self.retunes = 0

    def _observe(self, now: float) -> None:
        self.est.update(
            EstimatorInputs(
                fault_counts={k: float(v) for k, v in self.counts.items()},
                ckpt_cost_s=self.ckpt_cost_s,
                recovery_cost_s=self.recovery_cost_s,
            ),
            now=now,
        )

    def next_interval(self, now: float) -> float:
        self._observe(now)
        tau = self.est.tau_opt()
        if not math.isinf(tau):
            # the controller's rule: never act before a fault is measured
            if self.act.set_cadence(tau, "bench sim") is not None:
                self.retunes += 1
        applied = self.act.current_cadence_s()
        return applied if applied else self.default_interval_s

    def recover(self, now: float, kind: str) -> float:
        self.counts[kind] += 1
        if kind == "exception":
            self.led.record("exception", "in_process", True, EXC_RECOVERY_S)
            self.recovery_cost_s = EXC_RECOVERY_S
            self._observe(now)
            return EXC_RECOVERY_S
        cost, episodes = walk_ladder(self.led.pick_start_rung("hang"))
        for rung, success, rung_cost in episodes:
            self.led.record("hang", rung, success, rung_cost)
        self.recovery_cost_s = cost
        self._observe(now)
        return cost

    def on_save(self, now: float, cost_s: float) -> None:
        self.ckpt_cost_s = cost_s


def simulate(fault_events: list, policy) -> float:
    """Run the save/fault loop; returns goodput (committed work fraction
    of wall time).  Work commits only at a completed save; a fault before
    the save COMPLETES (including inside the save window) wipes the
    uncommitted tail and costs the policy's recovery."""
    t = 0.0
    committed = 0.0
    uncommitted = 0.0
    fi = 0
    while t < TOTAL_S:
        interval = max(1.0, policy.next_interval(t))
        save_end = t + interval + CKPT_COST_S
        if fi < len(fault_events) and fault_events[fi][0] < min(save_end, TOTAL_S):
            tf, kind = fault_events[fi]
            fi += 1
            uncommitted = 0.0
            t = tf + policy.recover(tf, kind)
            continue
        if save_end >= TOTAL_S:
            break  # run ends mid-interval; the tail never committed
        uncommitted += interval
        t = save_end
        committed += uncommitted
        uncommitted = 0.0
        policy.on_save(t, CKPT_COST_S)
    return committed / TOTAL_S


def run_trial(seed: int) -> dict:
    fault_events = draw_fault_times(seed)
    fixed = {}
    for interval in FIXED_GRID_S:
        env.clear_runtime_overrides()
        fixed[interval] = simulate(fault_events, FixedPolicy(interval))
    best_fixed_interval = max(fixed, key=fixed.get)
    best_fixed = fixed[best_fixed_interval]

    env.clear_runtime_overrides()
    # production clamp floors would pin the noisy-phase optimum (~15 s)
    env.set_runtime_override(env.POLICY_CADENCE_MIN_S.name, "2.0")
    env.set_runtime_override(env.POLICY_CADENCE_MAX_S.name, "300.0")
    env.set_runtime_override(env.POLICY_HYSTERESIS_PCT.name, "10.0")
    adaptive_policy = AdaptivePolicy(window_s=200.0, default_interval_s=30.0)
    try:
        adaptive = simulate(fault_events, adaptive_policy)
    finally:
        env.clear_runtime_overrides()

    gain = adaptive / max(best_fixed, 1e-9)
    n_exc = sum(1 for _t, k in fault_events if k == "exception")
    n_hang = sum(1 for _t, k in fault_events if k == "hang")
    return {
        "seed": seed,
        "faults_injected": {"exception": n_exc, "hang": n_hang},
        "adaptive_goodput": round(adaptive, 4),
        "best_fixed_goodput": round(best_fixed, 4),
        "best_fixed_interval_s": best_fixed_interval,
        "fixed_sweep": {str(k): round(v, 4) for k, v in fixed.items()},
        "retunes": adaptive_policy.retunes,
        "hang_start_rung": adaptive_policy.led.pick_start_rung("hang"),
        "gain": round(gain, 3),
    }


def run(seed: int, trials: int = 3) -> dict:
    """Gate on the MEAN gain over ``trials`` derived schedules, so the
    verdict reflects the policy, not one lucky fault draw.  Fully
    deterministic for a given (seed, trials)."""
    # thousands of simulated retunes; keep stdout to the one JSON line
    logging.getLogger("tpurx.policy.actuator").setLevel(logging.WARNING)
    results = [run_trial(seed + 101 * i) for i in range(max(1, trials))]
    mean_gain = sum(r["gain"] for r in results) / len(results)
    return {
        "metric": "bench_policy",
        "seed": seed,
        "trials": len(results),
        "policy_adaptive_goodput": round(
            sum(r["adaptive_goodput"] for r in results) / len(results), 4),
        "policy_best_fixed_goodput": round(
            sum(r["best_fixed_goodput"] for r in results) / len(results), 4),
        "policy_trial_gains": [r["gain"] for r in results],
        "policy_retunes": sum(r["retunes"] for r in results),
        "policy_hang_start_rung": results[-1]["hang_start_rung"],
        "policy_trials": results,
        "policy_goodput_gain": round(mean_gain, 3),
        "policy_ok": bool(mean_gain >= 1.1),
        "ok": bool(mean_gain >= 1.1),
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seed", type=int, default=0xA11CE)
    p.add_argument("--trials", type=int, default=3)
    args = p.parse_args()
    report = run(args.seed, args.trials)
    print(json.dumps(report))
    sys.exit(0 if report["ok"] else 1)


if __name__ == "__main__":
    main()
